"""Headline benchmark: the deps data plane, device vs host, measured four ways.

BASELINE.md names the target metrics: "Maelstrom rw-register txns/sec; p50
PreAccept deps-calc latency", with configs for a contended e2e run, a
synthetic PreAccept batch at 10k in-flight txns, and a 100k-node execute
DAG. This bench measures all of them:

1. `pipeline` (THE HEADLINE): p50 PreAccept deps-calc latency against a
   REAL CommandStore pre-loaded with 10k in-flight txns over 1k hot keys
   (BASELINE "Synthetic PreAccept batch"). The host leg runs the
   reference-style per-key registry scan; the device leg runs the batched
   arena kernel (amortized per-subject blocking cost, which through the
   tunnelled TPU is readback-bandwidth-bound -- a local chip pays ~us).
   Device results are differentially checked against the host scan.
2. `e2e`: the contended rw-register analog (5 nodes, 4-key Zipfian writes,
   ~1k concurrent, strict-serializability verifier ON) run twice on the
   identical workload -- host resolver vs device resolver. Through the
   tunnel this number is dominated by the Python protocol simulator and the
   80ms simulated harvest latency, so it mostly proves the async device
   plane does not LOSE throughput while the per-call deps cost drops ~10x.
3. `dag`: execution wavefronts of a 100k-node random dependency DAG
   (BASELINE "Synthetic Execute DAG") via dag_wavefronts_packed, with the
   identical packed-word algorithm in NumPy as the host baseline
   (per-round comparison; the DAG is generated ON DEVICE -- uploading a
   1.25GB adjacency over the tunnel would measure the link, not the
   kernel).
4. `maelstrom`: the in-process Maelstrom runner (production node code path,
   JSON packets, base64 transport) at 1k+ txns -- txns/sec with every
   reply checked. The external invocation is
   `maelstrom test -w txn-list-append --bin maelstrom/serve.sh` (see
   accord_tpu/maelstrom/README snippet in core.py).

Prints ONE JSON line; any exception prints a parseable error line and
exits 1.

Usage: python bench.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np

E2E_BUCKETS = 1024
E2E_ARENA_CAP = 2048
HOT_KEYS = 16

PIPE_ACTIVE = 10_000       # in-flight txns pre-loaded into the store
PIPE_KEYS = 1_000          # hot-key domain (BASELINE: 1k keys)
PIPE_SUBJECTS = 4_096       # deps queries measured (sustained pipeline)
# dispatch size: each dispatch pays one tunnel/interconnect round trip, so
# the per-subject blocking cost is ~RTT/batch + decode; 1024 keeps the
# number honest under tunnel-latency swings (10k-concurrent coordination
# trivially fills 1024-deep windows)
PIPE_BATCH = 1_024
PIPE_CAP = 16_384
PIPE_BUCKETS = 1024

DAG_N = 100_000
DAG_LEVELS = 192
LARGE_REPLAY_OPS = 100_000  # BASELINE "YCSB-T-style large replay"

# --trace <base>: every top-level leg dumps a Perfetto-loadable trace to
# <base>.<leg>.json; bench_e2e additionally scopes one to its first device
# attempt and cross-checks the trace's hidden-overlap share against the
# registry's host_hidden_pct (set by main(), None = tracing off)
TRACE_BASE = None
TRACE_CAPACITY = 1 << 20


def _trace_start():
    from accord_tpu.obs.trace import REC
    REC.clear()
    REC.configure(capacity=TRACE_CAPACITY, wall=True)
    REC.enabled = True


def _trace_dump(leg: str) -> str:
    from accord_tpu.obs import export
    from accord_tpu.obs.trace import REC
    REC.enabled = False
    path = f"{TRACE_BASE}.{leg}.json"
    export.write_trace(path, REC.events())
    REC.clear()
    return path


def _traced(leg: str, fn, *args, **kwargs):
    """Run one bench leg with the flight recorder on, dumping its trace
    (no-op passthrough when --trace was not given)."""
    if TRACE_BASE is None:
        return fn(*args, **kwargs)
    _trace_start()
    try:
        return fn(*args, **kwargs)
    finally:
        _trace_dump(leg)


def _reconcile_trace(events, dropped: int, registry_pct: float,
                     path: str) -> dict:
    """Cross-check the traced device leg against the registry: the X spans'
    wall durations are the SAME perf_counter deltas the resolver timers
    accumulate, so the trace-derived hidden-overlap share must land within
    one percentage point of the registry's host_hidden_pct."""
    if dropped:
        raise AssertionError(
            f"flight recorder dropped {dropped} events during the traced "
            f"e2e leg; raise TRACE_CAPACITY")
    denom = 0.0
    hidden = 0.0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = ev.get("dur", 0.0)
        name = ev["name"]
        if name in ("preaccept", "encode", "launch", "decode"):
            denom += dur
        if name in ("stage_host", "decode") \
                and ev.get("args", {}).get("hidden"):
            hidden += dur
    trace_pct = 100.0 * hidden / denom if denom else 0.0
    if abs(trace_pct - registry_pct) > 1.0:
        raise AssertionError(
            f"trace/registry hidden-overlap mismatch: trace says "
            f"{trace_pct:.2f}%, registry says {registry_pct:.2f}%")
    return {"path": path, "events": len(events),
            "hidden_pct": round(trace_pct, 1),
            "registry_hidden_pct": round(registry_pct, 1)}


# ---------------------------------------------------------------------------
# 1. pipeline: 10k in-flight txns over 1k keys, real store
# ---------------------------------------------------------------------------

def bench_pipeline(quick: bool):
    from accord_tpu.local.cfk import CfkStatus
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
    from accord_tpu.sim.cluster import Cluster, ClusterConfig
    from accord_tpu.utils.rng import RandomSource

    active = 2_000 if quick else PIPE_ACTIVE
    subjects_n = 128 if quick else PIPE_SUBJECTS

    resolver = BatchDepsResolver(num_buckets=PIPE_BUCKETS, initial_cap=PIPE_CAP,
                                 max_dispatch=PIPE_BATCH,
                                 adaptive_window=True)
    cluster = Cluster(3, ClusterConfig(
        num_nodes=1, rf=1, stores_per_node=1, num_shards=1,
        progress=False, deps_resolver_factory=lambda: resolver,
        deps_batch_window_ms=None))
    node = cluster.nodes[1]
    store = node.command_stores.all()[0]
    rng = RandomSource(17)

    # pre-load the conflict registry: `active` writes over the hot keys
    load_t0 = time.perf_counter()
    for i in range(active):
        ts = node.unique_now()
        txn_id = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                              Domain.KEY)
        keys = Keys(rng.next_int(PIPE_KEYS) for _ in range(4))
        store.register(txn_id, keys, CfkStatus.WITNESSED, ts)
    load_s = time.perf_counter() - load_t0

    # subjects: fresh txns arriving on the loaded registry
    subjects = []
    for _ in range(subjects_n):
        ts = node.unique_now()
        txn_id = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                              Domain.KEY)
        keys = store.owned(Keys(rng.next_int(PIPE_KEYS) for _ in range(4)))
        subjects.append((txn_id, keys, ts))

    # host leg: the reference-style per-key scan
    host_samples = []
    host_results = []
    for txn_id, keys, before in subjects:
        t0 = time.perf_counter()
        host_results.append(store.host_calculate_deps(txn_id, keys, before))
        host_samples.append(time.perf_counter() - t0)

    # device leg, exactness: one sync batch differentially checked against
    # the host scan (compiles the batch tier as a side effect)
    check_n = min(64, subjects_n)
    dev_check = resolver.resolve_batch(store, subjects[:check_n])
    mismatches = sum(
        1 for h, d in zip(host_results[:check_n], dev_check)
        if set(h.key_deps.all_txn_ids()) != set(d.key_deps.all_txn_ids()))
    if mismatches:
        raise AssertionError(
            f"device deps diverge from host scan on {mismatches}/"
            f"{check_n} subjects")

    # device leg, throughput: the REAL async pipeline (dispatch windows +
    # deferred harvests overlapping the transfer + readiness polling),
    # exactly as the protocol consumes it. The protocol thread only ever
    # blocks on harvest stalls + result decode; the sustained rate is what
    # 10k-concurrent coordination sees.
    store.batch_window_ms = 2.0
    node.device_latency_ms = 80.0
    node.device_poll_ms = 1.0   # arm the prefetch poll (opt-in)
    stall0 = resolver.harvest_stall_s + resolver.decode_s
    done = [0]
    failed = [0]

    def completion(v, f):
        # successes only: a failed resolution must not count as completed
        if f is None:
            done[0] += 1
        else:
            failed[0] += 1

    t0 = time.perf_counter()
    for txn_id, keys, before in subjects:
        resolver.enqueue_deps(store, txn_id, keys, before) \
            .add_callback(completion)
    cluster.queue.drain(max_events=1_000_000)
    dev_wall = time.perf_counter() - t0
    if failed[0]:
        raise AssertionError(f"async pipeline failed {failed[0]} resolutions")
    if done[0] != subjects_n:
        raise AssertionError(f"async pipeline resolved {done[0]}/{subjects_n}")
    dev_block_us = (resolver.harvest_stall_s + resolver.decode_s - stall0) \
        / subjects_n * 1e6

    host_p50 = float(np.percentile(host_samples, 50) * 1e6)
    host_mean = float(np.mean(host_samples)) * 1e6

    # -- large replay (BASELINE "YCSB-T-style large replay"): stream >=100k
    # deps queries through the SAME loaded store with WINDOWED admission --
    # up to `window` ops outstanding at all times, so host-encode of the
    # next dispatch overlaps device-execute and host-decode of earlier ones
    # (a full drain per chunk would empty the pipeline at every boundary).
    # The host comparison is its measured serial scan rate (a serial replay
    # of the same op count).
    replay_ops = 10_000 if quick else LARGE_REPLAY_OPS
    chunk = 2 * PIPE_BATCH
    window = 2 * chunk      # >= 4 in-flight dispatches
    done = [0]
    failed = [0]
    pa0 = resolver.preaccept_s
    enc0 = resolver.encode_s
    disp0 = resolver.dispatch_s
    stall0 = resolver.harvest_stall_s
    dec0 = resolver.decode_s
    hid0 = resolver.host_hidden_s
    sd0 = resolver.staged_dispatches
    pre0 = resolver.prefetched
    stale0 = resolver.stale_harvests
    fall0 = resolver.host_fallbacks
    rb0 = resolver.readback_s
    mat0 = resolver.materialize_s
    fin0 = resolver.finalized_decodes
    leg0 = resolver.legacy_decodes
    ff0 = resolver.finalize_fallbacks
    ws0 = resolver.window_shrinks
    ww0 = resolver.window_widens
    from accord_tpu.ops.kernels import jit_cache_sizes
    cache0 = jit_cache_sizes()   # warmup must have covered every jit tier
    chunk_walls = []
    chunk_sizes = []
    enqueued = 0
    replay_t0 = time.perf_counter()
    for base in range(0, replay_ops, chunk):
        n = min(chunk, replay_ops - base)
        chunk_sizes.append(n)
        c0 = time.perf_counter()
        for _ in range(n):
            ts = node.unique_now()
            txn_id = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                                  Domain.KEY)
            keys = store.owned(Keys(rng.next_int(PIPE_KEYS) for _ in range(4)))
            resolver.enqueue_deps(store, txn_id, keys, ts) \
                .add_callback(completion)
            enqueued += 1
            while enqueued - done[0] - failed[0] >= window \
                    and cluster.queue.process_one():
                pass
        if base + n >= replay_ops:
            # final drain folds into the last chunk's wall
            cluster.queue.drain(max_events=2_000_000)
        chunk_walls.append(time.perf_counter() - c0)
    replay_wall = time.perf_counter() - replay_t0
    if failed[0]:
        raise AssertionError(f"large replay failed {failed[0]} resolutions")
    if done[0] != replay_ops:
        raise AssertionError(f"large replay resolved {done[0]}/{replay_ops}")
    if resolver.host_fallbacks != fall0:
        raise AssertionError(
            f"large replay hit {resolver.host_fallbacks - fall0} stale-arena "
            "host fallbacks (generation pinning should translate instead)")
    cache1 = jit_cache_sizes()
    if cache1 != cache0:
        raise AssertionError(
            f"jit tiers compiled inside the timed window: {cache0} -> "
            f"{cache1} (warmup coverage is stale)")
    # staged tick pipeline: launches must come off the encode-ahead list,
    # and some host-phase time must have run inside the device window
    staged_d = resolver.staged_dispatches - sd0
    if staged_d <= 0:
        raise AssertionError(
            "staged pipeline disengaged in the large replay "
            "(no encode-ahead launches)")
    # finalized-CSR harvest engaged for EVERY group: the legacy unpackbits
    # decode must not have run at all in the timed window
    if resolver.legacy_decodes != leg0:
        raise AssertionError(
            f"finalized path disengaged: {resolver.legacy_decodes - leg0} "
            "groups fell back to the legacy unpackbits decode in the "
            "large replay")
    if resolver.finalized_decodes == fin0:
        raise AssertionError(
            "finalized-CSR harvest never engaged in the large replay")
    # adaptive staged window: the bursty admission pattern must have moved
    # the per-node window scale at least once over the pipeline bench
    if resolver.window_shrinks + resolver.window_widens == 0:
        raise AssertionError(
            "adaptive window never adapted (no shrinks or widens across "
            "the pipeline bench)")
    phase_s = {
        "preaccept_s": resolver.preaccept_s - pa0,
        "encode_s": resolver.encode_s - enc0,
        "dispatch_s": resolver.dispatch_s - disp0,
        "decode_s": resolver.decode_s - dec0,
    }
    hidden_s = resolver.host_hidden_s - hid0
    phases_total = sum(phase_s.values())
    host_hidden_pct = 100.0 * hidden_s / phases_total if phases_total else 0.0
    if not hidden_s > 0:
        raise AssertionError(
            "no host-phase time was hidden inside the device window "
            "(host_hidden_s delta is zero)")
    per_op = np.asarray(chunk_walls) / np.asarray(chunk_sizes) * 1e6
    host_projected_s = replay_ops * (host_mean / 1e6)

    return {
        "active_txns": active,
        "keys": PIPE_KEYS,
        "subjects": subjects_n,
        "load_s": round(load_s, 2),
        "host_p50_us": round(host_p50, 1),
        "host_p99_us": round(float(np.percentile(host_samples, 99) * 1e6), 1),
        "host_mean_us": round(host_mean, 1),
        "host_throughput_per_s": round(1e6 / max(host_mean, 1e-3)),
        "device_block_us": round(dev_block_us, 1),
        "device_pipeline_wall_s": round(dev_wall, 2),
        "device_throughput_per_s": round(subjects_n / max(dev_wall, 1e-9)),
        "speedup_blocking": round(host_mean / max(dev_block_us, 1e-3), 2),
        "differential_checked": check_n,
        "large_replay": {
            "ops": replay_ops,
            "chunk": chunk,
            "window": window,
            "device_wall_s": round(replay_wall, 1),
            "device_throughput_per_s": round(replay_ops / max(replay_wall, 1e-9)),
            # amortized per-op cost distribution over admission chunks
            "per_op_us": {
                "p50": round(float(np.percentile(per_op, 50)), 1),
                "p99": round(float(np.percentile(per_op, 99)), 1),
                "p999": round(float(np.percentile(per_op, 99.9)), 1),
            },
            # pipeline-stage costs over the replay (deltas): the three host
            # stages plus decode, and how much of that total ran while a
            # device call was already in flight (hidden by the staged tick)
            "preaccept_s": round(phase_s["preaccept_s"], 2),
            "encode_s": round(phase_s["encode_s"], 2),
            "dispatch_s": round(phase_s["dispatch_s"], 2),
            "decode_s": round(phase_s["decode_s"], 2),
            # decode split: device->host transfer time vs host-side CSR
            # slice-and-wrap (the finalized path turns the latter into
            # searchsorted + array slicing over the compacted readback)
            "readback_s": round(resolver.readback_s - rb0, 2),
            "materialize_s": round(resolver.materialize_s - mat0, 2),
            "finalized_decodes": resolver.finalized_decodes - fin0,
            "legacy_decodes": resolver.legacy_decodes - leg0,
            "finalize_fallbacks": resolver.finalize_fallbacks - ff0,
            "window_shrinks": resolver.window_shrinks - ws0,
            "window_widens": resolver.window_widens - ww0,
            "harvest_stall_s": round(resolver.harvest_stall_s - stall0, 2),
            "host_hidden_s": round(hidden_s, 2),
            "host_hidden_pct": round(host_hidden_pct, 1),
            "staged_dispatches": staged_d,
            "prefetched": resolver.prefetched - pre0,
            "stale_harvests": resolver.stale_harvests - stale0,
            "host_fallbacks": resolver.host_fallbacks - fall0,
            "range_fallbacks": resolver.range_fallbacks,
            "upload_bytes": resolver.upload_bytes,
            "upload_bytes_by_field": resolver.upload_bytes_by_field,
            "recompiles_in_window": 0,                      # asserted above
            "host_serial_projected_s": round(host_projected_s, 1),
            "vs_host_serial": round(host_projected_s / max(replay_wall, 1e-9), 2),
            # per-phase view of the same ratio: each pipeline stage's cost
            # against the host-serial projection, so a regression in any one
            # stage (e.g. decode growing with window width) is visible even
            # while the overall vs_host_serial still clears its gate
            "vs_host_serial_by_phase": {
                p: round(host_projected_s / max(phase_s[f"{p}_s"], 1e-9), 1)
                for p in ("preaccept", "encode", "dispatch", "decode")
            },
        },
    }


# ---------------------------------------------------------------------------
# 2. e2e: contended rw-register analog, host vs device resolver
# ---------------------------------------------------------------------------

def bench_e2e_leg(seed: int, ops: int, concurrency: int, device: bool,
                  overlap: bool = True):
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    from accord_tpu.obs.metrics import MetricsRegistry

    resolvers = []
    factory = None
    host_reg = MetricsRegistry()  # host leg: per-scan latency histogram
    orig = None
    cache0 = None
    if device:
        from accord_tpu.ops.kernels import jit_cache_sizes
        from accord_tpu.ops.resolver import BatchDepsResolver

        def factory():
            r = BatchDepsResolver(num_buckets=E2E_BUCKETS,
                                  initial_cap=E2E_ARENA_CAP,
                                  max_dispatch=256, overlap_host=overlap)
            resolvers.append(r)
            return r

        cache0 = jit_cache_sizes()  # warmup covered the multi-store tiers
    else:
        import accord_tpu.local.store as store_mod
        orig = store_mod.CommandStore.host_calculate_deps

        def timed(self, txn_id, seekables, before):
            t0 = time.perf_counter()
            out = orig(self, txn_id, seekables, before)
            dt = time.perf_counter() - t0
            host_reg.timer("host.calc_deps_s").add(dt)
            host_reg.histogram("host.calc_deps_us").observe(dt * 1e6)
            return out

        store_mod.CommandStore.host_calculate_deps = timed

    cfg = ClusterConfig(
        num_nodes=5, rf=3,
        deps_resolver_factory=factory,
        # each dispatch pays one real interconnect round trip at harvest:
        # wider (simulated-time) coalescing windows amortize it without
        # costing wall clock
        deps_batch_window_ms=16.0 if device else 0.0,
        device_latency_ms=80.0,
        durability=True, durability_interval_ms=1000.0,
        timeout_ms=8000.0, preaccept_timeout_ms=8000.0,
        progress_stall_ms=5000.0,
    )
    t0 = time.perf_counter()
    try:
        report = run_burn(seed, ops=ops, key_count=HOT_KEYS, zipf_theta=0.99,
                          max_keys_per_txn=4, concurrency=concurrency,
                          write_ratio=0.7, config=cfg)
    finally:
        if not device:
            import accord_tpu.local.store as store_mod
            store_mod.CommandStore.host_calculate_deps = orig
    wall = time.perf_counter() - t0
    stats = {}
    if device:
        from accord_tpu.ops.kernels import jit_cache_sizes
        cache1 = jit_cache_sizes()
        # the finalize out-caps are hysteresis-pinned OutCapTiers rungs now
        # (warmed below), so finalize_csr/range_finalize_csr sit under the
        # strict zero-recompile assertion like everything else. Only the
        # kid-table dirty-word buckets stay exempt: their pow2 tiers follow
        # upload batch sizes, can mint at most once ever per shape, and are
        # unrelated to the finalize ladder.
        data_tiered = ("kid_word_scatter",)
        drift = {k: (cache0[k], cache1[k]) for k in cache1
                 if cache1[k] != cache0[k] and k not in data_tiered}
        if drift:
            raise AssertionError(
                f"jit tiers compiled inside the e2e burn: {drift} "
                "(warmup store_tiers/out_tiers coverage is stale)")
        # fold every resolver's registry into one: the merged snapshot is
        # the single source for the stats below (the legacy attribute reads
        # are descriptor views over these same cells)
        agg = MetricsRegistry()
        for r in resolvers:
            agg.merge_from(r.metrics)
        snap = agg.snapshot()

        def g(name, default=0):
            return snap.get("resolver." + name, default)

        dispatches = g("dispatches")
        ticks = g("ticks")
        # fused cross-store dispatch engaged: a per-store drain would pay
        # stores_per_node dispatches per tick
        if ticks and dispatches >= cfg.stores_per_node * ticks:
            raise AssertionError(
                f"fused dispatch disengaged: {dispatches} dispatches over "
                f"{ticks} ticks with {cfg.stores_per_node} stores/node")
        # finalized-CSR harvest engaged on the burn's device leg (legacy
        # decodes still legitimately run for groups caught by a mid-flight
        # truncation/compaction -- those are counted, not forbidden)
        if dispatches and g("finalized_decodes") == 0:
            raise AssertionError(
                "finalized-CSR harvest never engaged in the e2e burn")
        ub = sum(r.upload_bytes for r in resolvers)
        ube = sum(r.upload_bytes_full_equiv for r in resolvers)
        # field-granular deltas pay off on this status-bump-heavy burn:
        # actual upload bytes must be strictly below the full-row baseline
        if not ub < ube:
            raise AssertionError(
                f"granular uploads not below full-row baseline: "
                f"{ub} >= {ube}")
        # staged tick pipeline engaged (overlap legs): the launches must
        # come off the encode-ahead lists, not the serial fallback
        staged = g("staged_dispatches")
        if overlap and dispatches and staged == 0:
            raise AssertionError(
                "staged pipeline disengaged in the e2e burn "
                "(overlap_host=True but no encode-ahead launches)")
        if not overlap and staged:
            raise AssertionError(
                f"serial leg took {staged} staged launches")
        phases = (g("preaccept_s", 0.0) + g("encode_s", 0.0)
                  + g("dispatch_s", 0.0) + g("decode_s", 0.0))
        hidden = g("host_hidden_s", 0.0)
        by_field = {}
        for r in resolvers:
            for k, v in r.upload_bytes_by_field.items():
                by_field[k] = by_field.get(k, 0) + v
        stats = {
            "overlap_host": overlap,
            "dispatches": dispatches,
            "staged_dispatches": staged,
            "ticks": ticks,
            "dispatches_per_tick": round(dispatches / max(ticks, 1), 3),
            "subjects": g("subjects"),
            "preaccept_s": round(g("preaccept_s", 0.0), 2),
            "encode_s": round(g("encode_s", 0.0), 2),
            "dispatch_s": round(g("dispatch_s", 0.0), 2),
            "host_hidden_s": round(hidden, 2),
            "host_hidden_pct": round(100.0 * hidden / phases, 1)
            if phases else 0.0,
            "harvest_stall_s": round(g("harvest_stall_s", 0.0), 2),
            "decode_s": round(g("decode_s", 0.0), 2),
            "readback_s": round(g("readback_s", 0.0), 2),
            "materialize_s": round(g("materialize_s", 0.0), 2),
            "finalized_decodes": g("finalized_decodes"),
            "legacy_decodes": g("legacy_decodes"),
            "finalize_fallbacks": g("finalize_fallbacks"),
            "outcap_tier_switches": g("outcap_tier_switches"),
            "range_subject_device_decodes": g("range_subject_device_decodes"),
            "prefetched": g("prefetched"),
            "stale_harvests": g("stale_harvests"),
            "host_fallbacks": g("host_fallbacks"),
            "range_fallbacks": g("range_fallbacks"),
            "upload_bytes": ub,
            "upload_bytes_by_field": by_field,
            "upload_bytes_full_equiv": ube,
        }
    else:
        scan = host_reg.histogram("host.calc_deps_us").snapshot()
        stats = {
            "resolve_calls": scan["count"],
            "resolve_total_s": round(
                host_reg.timer("host.calc_deps_s").total, 2),
            "mean_scan_us": round(scan["mean"], 1),
            "scan_us": scan,
        }
    # sim-time txn lifecycle latencies, merged across the burn's nodes
    # (burn.py folds every node.metrics into report.registry)
    txn = report.registry.snapshot() if report.registry is not None else {}
    stats["txn_latency_us"] = {
        "commit": txn.get("txn.commit_latency_us"),
        "apply": txn.get("txn.apply_latency_us"),
    }
    return wall, report, stats


def bench_e2e(quick: bool):
    ops, concurrency = (200, 512) if quick else (800, 1024)
    host_wall, host_rep, host_stats = bench_e2e_leg(9, ops, concurrency, False)
    attempts = []
    for i in range(1 if quick else 2):
        if i == 0 and TRACE_BASE is not None:
            # trace the first device attempt and reconcile the trace's
            # hidden-overlap share against the registry's host_hidden_pct
            from accord_tpu.obs.trace import REC
            _trace_start()
            attempt = bench_e2e_leg(9, ops, concurrency, True)
            REC.enabled = False
            events = REC.events()
            dropped = REC.dropped
            path = _trace_dump("e2e_device")
            attempt[2]["trace"] = _reconcile_trace(
                events, dropped, attempt[2]["host_hidden_pct"], path)
            attempts.append(attempt)
        else:
            attempts.append(bench_e2e_leg(9, ops, concurrency, True))
    dev_wall, dev_rep, dev_stats = min(attempts, key=lambda a: a[0])
    dev_stats["attempt_walls_s"] = [round(a[0], 1) for a in attempts]
    # the serial-tick baseline (overlap_host=False): same workload, same
    # device path, host phases NOT overlapped with the in-flight window
    ser_wall, ser_rep, ser_stats = bench_e2e_leg(9, ops, concurrency, True,
                                                 overlap=False)
    host_rate = host_rep.acked / host_wall
    dev_rate = dev_rep.acked / dev_wall
    ser_rate = ser_rep.acked / ser_wall
    return {
        "ops": ops,
        "concurrency": concurrency,
        "txns_per_sec": {"host": round(host_rate, 1),
                         "device": round(dev_rate, 1),
                         "device_serial_tick": round(ser_rate, 1),
                         "ratio": round(dev_rate / host_rate, 3),
                         "overlap_vs_serial": round(dev_rate / ser_rate, 3)},
        "wall_s": {"host": round(host_wall, 1), "device": round(dev_wall, 1),
                   "device_serial_tick": round(ser_wall, 1)},
        "acked": {"host": host_rep.acked, "device": dev_rep.acked,
                  "device_serial_tick": ser_rep.acked},
        "failed": {"host": host_rep.failed, "device": dev_rep.failed,
                   "device_serial_tick": ser_rep.failed},
        "host": host_stats,
        "device": dev_stats,
        "device_serial_tick": ser_stats,
    }


# ---------------------------------------------------------------------------
# 2b. range-heavy mix: 20% range txns, fully device-resident deps
# ---------------------------------------------------------------------------

def bench_range_mix(quick: bool):
    """Contended burn with ~20% range-domain txns on the device path: range
    subjects and range conflicts resolve through the interval arena (no
    host_calculate_deps, no host_range_deps union), so the retired-residual
    counters must stay zero; run twice (readiness poll armed) to prove
    polled burns replay bit-identically."""
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    ops = 150 if quick else 400

    def leg():
        resolvers = []

        def factory():
            r = BatchDepsResolver(num_buckets=E2E_BUCKETS,
                                  initial_cap=E2E_ARENA_CAP,
                                  max_dispatch=256)
            resolvers.append(r)
            return r

        cfg = ClusterConfig(
            num_nodes=5, rf=3,
            deps_resolver_factory=factory,
            deps_batch_window_ms=2.0, device_latency_ms=8.0,
            device_poll_ms=1.0,     # polled: the prefetch path under test
            durability=True, durability_interval_ms=1000.0,
            timeout_ms=8000.0, preaccept_timeout_ms=8000.0,
            progress_stall_ms=5000.0)
        t0 = time.perf_counter()
        rep = run_burn(21, ops=ops, key_count=HOT_KEYS, zipf_theta=0.99,
                       write_ratio=0.6, range_read_ratio=0.1,
                       range_write_ratio=0.1, collect_log=True, config=cfg)
        return time.perf_counter() - t0, rep, resolvers

    wall_a, rep_a, res_a = leg()
    wall_b, rep_b, _ = leg()
    if rep_a.log != rep_b.log:
        raise AssertionError("polled range-mix burn is not replay-identical")
    if rep_a.lost:
        raise AssertionError(f"range-mix burn lost {rep_a.lost} acked txns")
    counters = {
        "host_fallbacks": sum(r.host_fallbacks for r in res_a),
        "range_fallbacks": sum(r.range_fallbacks for r in res_a),
        # fully device-resident finalize: every group (range subjects
        # included) must decode from the device CSR -- zero guard trips,
        # zero legacy unpackbits decodes
        "finalize_fallbacks": sum(r.finalize_fallbacks for r in res_a),
        "legacy_decodes": sum(r.legacy_decodes for r in res_a),
    }
    bad = {k: v for k, v in counters.items() if v}
    if bad:
        raise AssertionError(f"range-mix burn left the device path: {bad}")
    rsub_dev = sum(r.range_subject_device_decodes for r in res_a)
    if rsub_dev == 0:
        raise AssertionError(
            "range-subject device stab never engaged in the range mix")
    return {
        "ops": ops,
        "range_ratio": 0.2,
        "acked": rep_a.acked,
        "failed": rep_a.failed,
        "wall_s": {"first": round(wall_a, 1), "replay": round(wall_b, 1)},
        "replay_identical": True,
        **counters,
        "range_subject_device_decodes": rsub_dev,
        "outcap_tier_switches": sum(r.outcap_tier_switches for r in res_a),
        "stale_harvests": sum(r.stale_harvests for r in res_a),
        "prefetched": sum(r.prefetched for r in res_a),
        "upload_bytes": sum(r.upload_bytes for r in res_a),
    }


# ---------------------------------------------------------------------------
# 2b2. device chaos: fault-injected device plane, bit-identical + bounded dip
# ---------------------------------------------------------------------------

CHAOS_RATES = {"dispatch_exc_rate": 0.06, "stuck_rate": 0.06,
               "corrupt_rate": 0.06, "overflow_rate": 0.02}


def bench_device_chaos(quick: bool):
    """Contended device-resolver burn under seeded device-plane fault
    injection (ops/fault_plane.py): dispatch exceptions, stuck harvests,
    corrupted readbacks, out-cap overflow storms. Proves the hardening
    claims end to end: every corrupted harvest is caught by the checksum
    lane before decode, the health ladder quarantines AND recovers nodes
    (probation canaries re-enter the device path against warmed tiers, so
    the measured leg mints zero compiles), two chaos runs reconcile
    bit-identically, the fault-free run of the same seed commits the SAME
    history, and the chaos throughput dip stays bounded."""
    from accord_tpu.ops.kernels import jit_cache_sizes
    from accord_tpu.ops.resolver import BatchDepsResolver
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    ops = 150 if quick else 400

    def leg(chaos: bool):
        resolvers = []

        def factory():
            r = BatchDepsResolver(num_buckets=E2E_BUCKETS,
                                  initial_cap=E2E_ARENA_CAP,
                                  max_dispatch=256)
            resolvers.append(r)
            return r

        cfg = ClusterConfig(
            num_nodes=5, rf=3,
            deps_resolver_factory=factory,
            deps_batch_window_ms=2.0, device_latency_ms=8.0,
            durability=True, durability_interval_ms=1000.0,
            timeout_ms=8000.0, preaccept_timeout_ms=8000.0,
            progress_stall_ms=5000.0)
        t0 = time.perf_counter()
        rep = run_burn(31, ops=ops, key_count=HOT_KEYS, zipf_theta=0.99,
                       write_ratio=0.7, device_chaos=chaos,
                       device_fault_rates=CHAOS_RATES if chaos else None,
                       collect_log=True, config=cfg)
        return time.perf_counter() - t0, rep, resolvers

    wall_a, rep_a, res_a = leg(True)      # warm + reconcile reference
    cache0 = jit_cache_sizes()
    wall_b, rep_b, res_b = leg(True)      # measured chaos leg
    cache1 = jit_cache_sizes()
    wall_c, rep_c, _ = leg(False)         # fault-free, same seed
    if rep_a.log != rep_b.log:
        raise AssertionError("chaos burn is not reconcile-identical")
    if rep_b.log != rep_c.log:
        raise AssertionError(
            "chaos burn's committed history diverged from the fault-free "
            "run of the same seed")
    if rep_b.lost:
        raise AssertionError(f"chaos burn lost {rep_b.lost} acked txns")
    # probation canaries re-enter the device path against tiers the burn
    # already warmed: recovery mints zero compiles (kid-table dirty-word
    # buckets exempt as in bench_e2e -- data-tiered, once-ever)
    drift = {k: (cache0.get(k, 0), v) for k, v in cache1.items()
             if v != cache0.get(k, 0) and k != "kid_word_scatter"}
    if drift:
        raise AssertionError(
            f"jit tiers compiled inside the measured chaos leg: {drift}")

    def agg(name):
        return sum(getattr(r, name) for r in res_b)

    injected = rep_b.device_faults
    total = sum(injected.values())
    if agg("device_faults_injected") != total:
        raise AssertionError(
            f"injection ledger mismatch: plane says {total}, resolvers "
            f"counted {agg('device_faults_injected')}")
    if any(injected[k] == 0 for k in injected):
        raise AssertionError(f"a fault kind never fired: {injected}")
    # every corrupted readback caught by the checksum lane before decode
    if agg("checksum_mismatches") != injected["corrupt"]:
        raise AssertionError(
            f"checksum lane missed corruption: {injected['corrupt']} "
            f"injected, {agg('checksum_mismatches')} caught")
    if agg("device_watchdog_trips") == 0:
        raise AssertionError("no stuck call ever tripped the watchdog")
    # the health ladder must complete full quarantine round trips:
    # entries AND exits (probation canaries passing)
    if agg("quarantine_entries") == 0 or agg("quarantine_exits") < 1:
        raise AssertionError(
            f"quarantine ladder did not round-trip: "
            f"{agg('quarantine_entries')} entries, "
            f"{agg('quarantine_exits')} exits")
    # overflow storms bump the windowed OutCapTiers once each, not per
    # quiet dispatch in between: switch count stays near the storm count
    switches = agg("outcap_tier_switches")
    if switches > 2 * injected["overflow"] + 8:
        raise AssertionError(
            f"out-cap tier flapping: {switches} switches for "
            f"{injected['overflow']} overflow storms")
    # bounded throughput dip: chaos pays retries/host reroutes, not a
    # collapse (loose wall gate -- CI machines are noisy)
    dip = wall_b / max(wall_c, 1e-9)
    if dip > 3.0:
        raise AssertionError(
            f"chaos leg {wall_b:.1f}s vs fault-free {wall_c:.1f}s "
            f"(x{dip:.2f}): dip not bounded")
    dispatches = agg("dispatches")
    degraded = agg("degraded_dispatches")
    if dispatches and degraded > dispatches // 2:
        raise AssertionError(
            f"{degraded}/{dispatches} dispatches degraded to host: the "
            f"device plane effectively fell over")
    return {
        "ops": ops,
        "rates": CHAOS_RATES,
        "acked": rep_b.acked,
        "failed": rep_b.failed,
        "injected": dict(injected),
        "wall_s": {"chaos": round(wall_b, 1), "fault_free": round(wall_c, 1),
                   "warm": round(wall_a, 1)},
        "throughput_dip": round(dip, 2),
        "reconcile_identical": True,
        "history_identical_to_fault_free": True,
        "dispatches": dispatches,
        "degraded_dispatches": degraded,
        "device_retries": agg("device_retries"),
        "device_watchdog_trips": agg("device_watchdog_trips"),
        "checksum_mismatches": agg("checksum_mismatches"),
        "quarantine_entries": agg("quarantine_entries"),
        "quarantine_exits": agg("quarantine_exits"),
        "device_canaries": agg("device_canaries"),
        "outcap_tier_switches": switches,
        "finalized_decodes": agg("finalized_decodes"),
    }


# ---------------------------------------------------------------------------
# 2c. pad_store_tiers: fixed fused jit tier across participating-store counts
# ---------------------------------------------------------------------------

def bench_pad_tiers(quick: bool):
    """Opt-in fused-dispatch padding on a 3-store node whose ticks touch a
    VARYING number of stores. Unpadded, each participating-store count mints
    its own fused jit tier; with pad_store_tiers=3 every fused call tops up
    to the one pre-warmed 3-block tier with empty arena blocks, so the fused
    compile counts must not move. Every answer is differentially checked."""
    from accord_tpu.local.cfk import CfkStatus
    from accord_tpu.ops.kernels import jit_cache_sizes
    from accord_tpu.ops.resolver import BatchDepsResolver, warmup
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
    from accord_tpu.sim.cluster import Cluster, ClusterConfig
    from accord_tpu.utils.rng import RandomSource

    buckets, cap = 128, 256
    fused_kerns = ("fused_deps_resolve", "fused_range_deps_resolve")
    # warm ONLY store tiers (1, 3): the padded leg needs nothing else; the
    # unpadded leg's 2-store fused calls are deliberately left cold so its
    # recompiles are visible
    warmup(num_buckets=buckets, cap=cap, batch_tiers=(8,),
           scatter_tiers=(8, 64), nnz_tiers=(32,), store_tiers=(1, 3))

    def leg(pad):
        cluster = Cluster(7, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                           stores_per_node=3, progress=False))
        node = cluster.nodes[1]
        stores = node.command_stores.stores
        resolver = BatchDepsResolver(num_buckets=buckets, initial_cap=cap,
                                     pad_store_tiers=pad)
        for s in stores:
            s.deps_resolver = resolver
            s.batch_window_ms = 0.5
        node.device_latency_ms = 5.0
        rng = RandomSource(13)
        lows = [min(int(r.start) for r in s.ranges) for s in stores]
        for s, lo in zip(stores, lows):
            for _ in range(24):
                ts = node.unique_now()
                tid = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                                   Domain.KEY)
                keys = Keys(sorted({lo + rng.next_int(64) for _ in range(2)}))
                s.register(tid, keys, CfkStatus.WITNESSED, ts)
        cache0 = jit_cache_sizes()
        checked = 0
        # waves alternating 2-of-3 and 3-of-3 participating stores: the
        # store-count axis the padding collapses
        for wave, wave_stores in enumerate(
                [stores[:2], stores, stores[1:], stores] * 2):
            subs, outs = [], []
            for s, lo in zip(wave_stores,
                             [lows[stores.index(x)] for x in wave_stores]):
                ts = node.unique_now()
                tid = TxnId.create(ts.epoch, ts.hlc, ts.node, TxnKind.WRITE,
                                   Domain.KEY)
                keys = s.owned(Keys(sorted(
                    {lo + rng.next_int(64) for _ in range(2)})))
                subs.append((s, tid, keys, ts))
                outs.append(resolver.enqueue_deps(s, tid, keys, ts))
            cluster.queue.drain(max_events=100_000)
            for (s, tid, keys, before), out in zip(subs, outs):
                assert out.done
                if out.value() != s.host_calculate_deps(tid, keys, before):
                    raise AssertionError(
                        f"pad leg (pad={pad}) diverges from host on {tid}")
                checked += 1
        cache1 = jit_cache_sizes()
        recompiles = sum(cache1[k] - cache0[k] for k in fused_kerns)
        return {"fused_recompiles": recompiles,
                "padded_dispatches": resolver.padded_dispatches,
                "dispatches": resolver.dispatches,
                "host_fallbacks": resolver.host_fallbacks,
                "differential_checked": checked}

    padded = leg(3)
    if padded["fused_recompiles"] != 0:
        raise AssertionError(
            f"padded leg minted {padded['fused_recompiles']} fused jit "
            "tiers (pad_store_tiers should pin one compiled shape)")
    if padded["padded_dispatches"] == 0:
        raise AssertionError("padding never engaged (no 2-of-3-store ticks?)")
    unpadded = leg(None)
    if unpadded["fused_recompiles"] == 0:
        raise AssertionError(
            "unpadded leg minted no fused tiers -- the padded leg's "
            "zero-recompile assertion is vacuous")
    return {"padded": padded, "unpadded": unpadded}


# ---------------------------------------------------------------------------
# 2d. exec plane: field-granular wait-graph deltas
# ---------------------------------------------------------------------------

def bench_exec_plane(quick: bool):
    """Burn with the device execution scheduler load-bearing: the wait-graph
    arena's status-bump traffic (executeAt, applied/pending flips) must ship
    single lanes through the shared flush_lane helper, strictly undercutting
    the retired whole-row scheme (upload_bytes_full_equiv)."""
    from accord_tpu.ops.exec_plane import ExecPlane
    from accord_tpu.sim.burn import run_burn
    from accord_tpu.sim.cluster import ClusterConfig

    ops = 100 if quick else 400
    planes = []
    orig_init = ExecPlane.__init__

    def spy(self, *a, **kw):
        orig_init(self, *a, **kw)
        planes.append(self)

    ExecPlane.__init__ = spy
    try:
        t0 = time.perf_counter()
        rep = run_burn(31, ops=ops, key_count=HOT_KEYS, zipf_theta=0.99,
                       config=ClusterConfig(exec_plane=True, durability=True,
                                            durability_interval_ms=1000.0))
        wall = time.perf_counter() - t0
    finally:
        ExecPlane.__init__ = orig_init
    if rep.lost:
        raise AssertionError(f"exec-plane burn lost {rep.lost} acked txns")
    ub = sum(p.upload_bytes for p in planes)
    ube = sum(p.upload_bytes_full_equiv for p in planes)
    by_field = {}
    for p in planes:
        for k, v in p.upload_bytes_by_field.items():
            by_field[k] = by_field.get(k, 0) + v
    if by_field.get("ts", 0) + by_field.get("flags", 0) == 0:
        raise AssertionError(
            "exec plane shipped no granular lane deltas (every update "
            "took the full-row path)")
    if not ub < ube:
        raise AssertionError(
            f"exec-plane granular uploads not below full-row baseline: "
            f"{ub} >= {ube}")
    return {
        "ops": ops,
        "acked": rep.acked,
        "failed": rep.failed,
        "wall_s": round(wall, 1),
        "planes": len(planes),
        "releases": sum(p.releases for p in planes),
        "dispatches": sum(p.dispatches for p in planes),
        "upload_bytes": ub,
        "upload_bytes_by_field": by_field,
        "upload_bytes_full_equiv": ube,
        "granular_saving_pct": round(100.0 * (1 - ub / ube), 1),
    }


def bench_cmd_plane(quick: bool):
    """Device command plane at 10k in-flight: PreAccept -> Commit -> Apply
    streams over two same-seed single-node clusters, Python handlers vs the
    SoA arena in arena-only mode (cmd_tick(promote=True) authoritative, no
    host residuals). Gates: the decision histories (outcome + executeAt per
    op, final executeAt per txn) are bit-identical, committed-txn/s clears
    3x the handler baseline, and the timed window mints zero cmd_tick
    compiles past warmup."""
    import random as _random

    from accord_tpu.ops.cmd_plane import CmdOp, CmdPlane, warmup_cmd_plane
    from accord_tpu.ops.kernels import CMD_ST_APPLIED, jit_cache_sizes
    from accord_tpu.primitives.deps import Deps
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn
    from accord_tpu.sim.cluster import Cluster, ClusterConfig
    from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate

    n = 2_000 if quick else 10_000
    key_space = 256
    chunk = 512
    arena_cap = 16_384

    def mk_env():
        cluster = Cluster(1, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                           stores_per_node=1, progress=False))
        node = cluster.nodes[1]
        return cluster, node, node.command_stores.stores[0]

    def mk_txns(node, store):
        # identical streams per leg: same RNG, same mint order (all ids up
        # front, matching the batched leg's clock evolution)
        rng = _random.Random(11)
        out = []
        for v in range(n):
            keys = Keys(sorted(rng.sample(range(1, key_space + 1),
                                          rng.randint(1, 3))))
            txn = Txn(TxnKind.WRITE, keys, read=ListRead(keys),
                      update=ListUpdate(keys, v), query=ListQuery())
            tid = node.next_txn_id(txn.kind, txn.domain)
            out.append((tid, txn, node.compute_route(txn),
                        txn.slice(store.ranges, include_query=False)))
        return out

    # -- host baseline: the engine's cmd_plane=False path (the store entry
    # points the message handlers call: submit_preaccept/commit_op/apply_op
    # with full registration + listener + execution bookkeeping) -----------
    _hc, hnode, hstore = mk_env()
    htxns = mk_txns(hnode, hstore)
    hist_host, eas = [], {}
    t0 = time.perf_counter()
    for tid, txn, route, part in htxns:
        got = {}
        hstore.submit_preaccept(tid, part, route) \
            .on_success(lambda v, g=got: g.update(v=v))
        ea = hstore.command(tid).execute_at
        eas[tid] = ea
        hist_host.append(("pa", got["v"][0], ea))
    pa_host = time.perf_counter() - t0
    t0 = time.perf_counter()
    for tid, txn, route, part in htxns:
        out = hstore.commit_op(tid, route, part, eas[tid], Deps.NONE)
        hist_host.append(("cm", out, hstore.command(tid).execute_at))
    cm_host = time.perf_counter() - t0
    t0 = time.perf_counter()
    for tid, txn, route, part in htxns:
        out = hstore.apply_op(tid, route, part, eas[tid], Deps.NONE,
                              None, None)
        hist_host.append(("ap", out, hstore.command(tid).execute_at))
    ap_host = time.perf_counter() - t0
    host_final = {tid: hstore.command(tid).execute_at for tid, *_ in htxns}

    # -- device leg: arena-only plane, chunked dispatches -------------------
    warm0 = time.perf_counter()
    warmup_cmd_plane(caps=(arena_cap,), key_caps=(1024,), kpad=4,
                     op_tiers=(chunk,), promote_modes=(True,))
    warm_s = time.perf_counter() - warm0
    cache0 = jit_cache_sizes()

    _dc, dnode, dstore = mk_env()
    dtxns = mk_txns(dnode, dstore)
    if [t[0] for t in dtxns] != [t[0] for t in htxns]:
        raise AssertionError("legs minted divergent txn id streams")
    plane = CmdPlane(dstore, initial_cap=arena_cap, key_cap=1024, kpad=4,
                     apply_to_store=False)
    hist_dev, deas = [], {}

    def phase(tag, mk_op):
        t0 = time.perf_counter()
        for i in range(0, n, chunk):
            span = dtxns[i:i + chunk]
            res = plane.eval_batch([mk_op(*t) for t in span])
            for (tid, *_), r in zip(span, res):
                if tag == "pa":
                    deas[tid] = r.execute_at
                hist_dev.append((tag, r.outcome, r.execute_at))
        return time.perf_counter() - t0

    pa_dev = phase("pa", lambda tid, txn, route, part:
                   CmdOp.preaccept(tid, part, route))
    cm_dev = phase("cm", lambda tid, txn, route, part:
                   CmdOp.commit(tid, route, part, deas[tid], Deps.NONE))
    ap_dev = phase("ap", lambda tid, txn, route, part:
                   CmdOp.apply(tid, route, part, deas[tid], Deps.NONE))
    cache1 = jit_cache_sizes()

    # -- gates --------------------------------------------------------------
    if cache1["cmd_tick"] != cache0["cmd_tick"]:
        raise AssertionError(
            f"cmd_tick recompiled inside the timed window: "
            f"{cache0['cmd_tick']} -> {cache1['cmd_tick']}")
    if plane.fallbacks:
        raise AssertionError(
            f"{plane.fallbacks} ops fell back to the host handlers (the "
            f"arena-only leg must run fully on device to be a fair clock)")
    if hist_dev != hist_host:
        diverged = next(i for i, (a, b) in
                        enumerate(zip(hist_host, hist_dev)) if a != b)
        raise AssertionError(
            f"decision histories diverged at op {diverged}: "
            f"host {hist_host[diverged]} dev {hist_dev[diverged]}")
    for tid, row in plane.row_of.items():
        if plane.status_h[row] != CMD_ST_APPLIED:
            raise AssertionError(f"{tid} did not reach APPLIED in the arena")
        import accord_tpu.ops.cmd_plane as _cp
        if _cp._dec(*(int(x) for x in plane.ea_h[row])) != host_final[tid]:
            raise AssertionError(f"final executeAt diverged for {tid}")

    host_committed_s = pa_host + cm_host
    dev_committed_s = pa_dev + cm_dev
    host_rate = n / max(host_committed_s, 1e-9)
    dev_rate = n / max(dev_committed_s, 1e-9)
    speedup = dev_rate / max(host_rate, 1e-9)
    # the 3x claim is pinned at 10k in-flight (the handler baseline's cfk
    # bookkeeping deepens with in-flight count; at quick's 2k the gap is
    # structurally narrower, so quick only smoke-gates the direction)
    gate = 1.2 if quick else 3.0
    if speedup < gate:
        raise AssertionError(
            f"cmd plane committed-txn/s only {speedup:.2f}x the Python "
            f"handlers ({dev_rate:.0f}/s vs {host_rate:.0f}/s; "
            f"gate {gate}x)")
    return {
        "inflight": n,
        "chunk": chunk,
        "arena_cap": arena_cap,
        "warmup_s": round(warm_s, 2),
        "host_s": {"preaccept": round(pa_host, 2), "commit": round(cm_host, 2),
                   "apply": round(ap_host, 2)},
        "device_s": {"preaccept": round(pa_dev, 2), "commit": round(cm_dev, 2),
                     "apply": round(ap_dev, 2)},
        "host_committed_per_s": round(host_rate),
        "device_committed_per_s": round(dev_rate),
        "committed_speedup": round(speedup, 2),
        "dispatches": plane.dispatches,
        "fastpath_device_evals": plane.fastpath_device_evals,
        "upload_bytes": plane.upload_bytes,
        "fallbacks": plane.fallbacks,
        "differential_ops": len(hist_host),
        "recompiles_in_window": 0,               # asserted above
    }


# ---------------------------------------------------------------------------
# 3. dag: 100k-node execute DAG wavefronts
# ---------------------------------------------------------------------------

def bench_dag(quick: bool):
    import jax
    import jax.numpy as jnp
    from accord_tpu.ops.kernels import dag_wavefronts_packed

    n = 24_576 if quick else DAG_N
    words = n // 32
    # AND of `thin` random u32 draws ~ density 2^-thin; target ~12 deps/node
    # (deps/node = density * n/2)
    thin = max(4, round(np.log2(n / 2 / 12)))

    @jax.jit
    def gen(key):
        adj = jnp.full((n, words), 0xFFFFFFFF, jnp.uint32)
        keys = jax.random.split(key, thin)
        for k in keys:
            adj &= jax.random.bits(k, (n, words), jnp.uint32)
        # lower-triangular mask: node w may only depend on d < w
        w_idx = jnp.arange(n)[:, None]
        j_idx = jnp.arange(words)[None, :]
        full = ((j_idx + 1) * 32 <= w_idx)
        partial = jnp.where(j_idx == w_idx // 32,
                            (jnp.uint32(1) << (w_idx % 32).astype(jnp.uint32))
                            - jnp.uint32(1),
                            jnp.uint32(0))
        mask = jnp.where(full, jnp.uint32(0xFFFFFFFF), partial)
        return adj & mask

    adj = gen(jax.random.PRNGKey(5))
    adj.block_until_ready()
    edges = int(jnp.sum(jax.vmap(
        lambda row: jnp.sum(jax.lax.population_count(row)))(adj)))

    # device: full settle
    levels = dag_wavefronts_packed(adj, DAG_LEVELS)
    levels.block_until_ready()   # compile
    t0 = time.perf_counter()
    levels = dag_wavefronts_packed(adj, DAG_LEVELS)
    depth = int(jnp.max(levels))
    settled = bool(jnp.min(levels) >= 0)
    dev_s = time.perf_counter() - t0

    # host baseline: identical packed-word algorithm in NumPy, per-round
    # cost measured over a few rounds (a full settle takes minutes)
    adj_np = np.asarray(adj)
    applied = np.zeros(words, np.uint32)
    level_np = np.full(n, -1, np.int64)
    rounds = 3
    t0 = time.perf_counter()
    for i in range(rounds):
        blocked = np.any(adj_np & ~applied[None, :], axis=1)
        ready = ~blocked & (level_np < 0)
        level_np[ready] = i
        packed = np.packbits(ready, bitorder="little").view(np.uint32)
        applied |= packed
    host_round_s = (time.perf_counter() - t0) / rounds
    host_projected_s = host_round_s * max(depth + 1, 1)

    return {
        "nodes": n,
        "edges": edges,
        "depth": depth,
        "settled": settled,
        "device_settle_s": round(dev_s, 3),
        "device_nodes_per_s": round(n / max(dev_s, 1e-9)),
        "host_round_s": round(host_round_s, 3),
        "host_projected_settle_s": round(host_projected_s, 1),
        "speedup": round(host_projected_s / max(dev_s, 1e-9), 1),
    }


# ---------------------------------------------------------------------------
# 4. maelstrom: in-process runner throughput
# ---------------------------------------------------------------------------

def bench_maelstrom(quick: bool):
    from accord_tpu.maelstrom.runner import Runner
    ops = 300 if quick else 1200
    runner = Runner(seed=5, num_nodes=3)
    t0 = time.perf_counter()
    stats = runner.run_random_workload(ops=ops, keys=12)
    wall = time.perf_counter() - t0
    return {
        "workload": "txn-list-append (rw-register analog)",
        "ops": ops,
        "txn_ok": stats["txn_ok"],
        "errors": stats["errors"],
        "reads_checked": stats["reads_checked"],
        "wall_s": round(wall, 1),
        "txns_per_sec": round(stats["txn_ok"] / wall, 1),
        "external_invocation":
            "maelstrom test -w txn-list-append --bin maelstrom/serve.sh "
            "--node-count 3 --time-limit 30 --rate 100 (wrapper shipped at "
            "maelstrom/serve.sh and exercised as a 3-process stdio cluster "
            "by tests/test_maelstrom.py; the maelstrom jar/JVM is not in "
            "this image)",
    }


# ---------------------------------------------------------------------------
# 5. serve: 3-process socket cluster under an open-loop offered-load sweep
# ---------------------------------------------------------------------------

def bench_serve(quick: bool):
    """The real serving surface: three `accord_tpu.serve` OS processes on
    loopback TCP, swept by the open-loop Poisson harness at half, full, and
    2x the cluster's admission capacity. The overload leg is the point:
    admission control answers the excess with BUSY instead of queueing it,
    so the latency of ADMITTED work stays in the operating region (asserted
    as overload-p99 <= 5x half-load-p99, with busy > 0 proving load
    actually shed). The whole run is one list-append history checked by
    the sim's strict-serializability verifier against the merged final key
    lists, and every node's jit cache must be byte-stable from the end of
    leg 1 to the end of the sweep (zero post-warmup recompiles)."""
    import asyncio
    import socket
    import subprocess

    from accord_tpu.serve.loadgen import LoadClient, LoadGen, verify_history

    # Admission capacity must sit BELOW the cluster's real throughput on
    # this host (3 contending CPU-jax processes sustain ~30 committed/s;
    # each admitted txn costs replica work on all three). Rate above real
    # capacity turns max_inflight into a standing queue and admitted-work
    # latency grows to depth/throughput -- exactly the collapse the
    # governor exists to prevent, so the bench config must not cause it.
    per_node_rate = 8.0    # admission capacity: 3 nodes x 8/s = 24/s
    capacity = 3 * per_node_rate
    leg_s = 6.0 if quick else 12.0
    legs = [("half", capacity * 0.5), ("full", capacity * 1.0),
            ("overload", capacity * 2.0)]

    socks = [socket.socket() for _ in range(3)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    peers = ",".join(f"{i + 1}=127.0.0.1:{p}" for i, p in enumerate(ports))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-m", "accord_tpu.serve",
         "--node-id", str(i + 1), "--listen", f"127.0.0.1:{port}",
         "--peers", peers, "--admission-rate", str(per_node_rate),
         "--admission-burst", "4", "--max-inflight", "8",
         "--metrics-interval-s", "600"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        for i, port in enumerate(ports)]
    addrs = {i + 1: ("127.0.0.1", p) for i, p in enumerate(ports)}

    async def drive():
        # startup includes the full kernel warmup: allow minutes, not
        # seconds, before declaring a node dead
        for host, port in addrs.values():
            deadline = time.monotonic() + 600.0
            while True:
                try:
                    _, w = await asyncio.open_connection(host, port)
                    w.close()
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise AssertionError(f"node :{port} never bound")
                    await asyncio.sleep(0.5)
        client = LoadClient(addrs)
        await client.connect()
        try:
            async def jit_caches():
                out = {}
                for nid in addrs:
                    s = await client.admin(nid, "stats")
                    out[nid] = s["jit_cache"]
                return out

            gen = LoadGen(client, seed=13, txn_timeout_s=20.0)
            results = {}
            jit_after_leg1 = None
            for name, rate in legs:
                results[name] = await gen.run_leg(rate, leg_s)
                if jit_after_leg1 is None:
                    jit_after_leg1 = await jit_caches()
                await asyncio.sleep(0.5)
            jit_final = await jit_caches()
            await asyncio.sleep(1.0)  # let applies land before snapshots
            lists_by_node = {}
            stats_by_node = {}
            for nid in addrs:
                kl = await client.admin(nid, "keylists")
                lists_by_node[nid] = kl["lists"]
                st = await client.admin(nid, "stats")
                stats_by_node[nid] = st["snapshot"]
            for nid in addrs:
                reply = await client.admin(nid, "shutdown", timeout_s=30)
                assert reply and reply["t"] == "shutdown_ok", reply
            return (results, jit_after_leg1, jit_final, lists_by_node,
                    stats_by_node, gen)
        finally:
            await client.close()

    try:
        (results, jit_after_leg1, jit_final, lists_by_node, stats_by_node,
         gen) = asyncio.run(drive())
        for p in procs:
            assert p.wait(timeout=15) == 0, "node exited non-zero"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)

    # -- gates ---------------------------------------------------------------
    half, over = results["half"], results["overload"]
    for name, leg in results.items():
        assert leg["ok"] > 0, (name, leg)
        assert leg["lost"] == 0, (name, leg)
    assert half["errors"] == 0, half
    assert over["busy"] > 0, \
        f"overload leg shed nothing through admission: {over}"
    assert over["p99_us"] <= 5.0 * half["p99_us"], \
        (f"admitted-work p99 blew up under overload: "
         f"{over['p99_us']}us vs {half['p99_us']}us at half load")
    assert jit_after_leg1 == jit_final, \
        f"post-warmup recompiles: {jit_after_leg1} -> {jit_final}"

    # one coherent history across the whole sweep, checked against the
    # merged (longest per key, prefix-consistent) final lists
    merged = {}
    for lists in lists_by_node.values():
        for k, v in lists.items():
            cur = merged.setdefault(k, v)
            short, long_ = (cur, v) if len(cur) <= len(v) else (v, cur)
            assert tuple(long_[:len(short)]) == tuple(short), \
                f"final lists diverged on key {k}"
            merged[k] = long_
    verify_history(gen.issues, gen.entries, final_lists=merged)

    sheds = sum(s.get("serve.admission_busy", 0)
                for s in stats_by_node.values())
    return {
        "cluster": "3 processes, loopback TCP, rf=3",
        "admission_capacity_per_s": capacity,
        "legs": results,
        "admission_busy_total": sheds,
        "verified_ok_txns": sum(leg["ok"] for leg in results.values()),
        "anomalies": 0,  # verify_history raises otherwise
        "jit_cache_stable": True,
        "overload_p99_vs_half": round(
            over["p99_us"] / max(half["p99_us"], 1.0), 2),
    }


# ---------------------------------------------------------------------------
# 5b. mesh burn: node id as a batch axis
# ---------------------------------------------------------------------------

def bench_mesh_burn(quick: bool):
    """Cluster-on-mesh burn sweep: same-seed burns at 8/64(/256) nodes,
    the node-lane merged dispatch vs the per-node Python launch loop.
    Hard gates per size: the two modes commit BIT-IDENTICAL event logs
    (so sim time is equal by construction and the comparison is purely
    about dispatch structure), committed txns per device dispatch clears
    3x the loop at >= 64 nodes (the loop fires one resolve kernel per
    node plan; the merge fires at most two per cluster tick -- on
    dispatch-bound accelerators this collapse IS the committed-txn/s
    win), and the FULL kernel surface -- `lane_slice` demux included,
    now that harvest spans pad to the node-block width tiers -- mints
    ZERO compiles in the timed sweep after the warm pass, across every
    node-count change.
    Wall-clock committed/s for both modes is reported un-gated: on CPU
    a dispatch is a function call, so the host-side block stacking can
    outweigh the collapse it buys; the structural ratio is the portable
    number. A MULTICHIP leg runs the same differential through
    `sharded_node_tick` on the host's virtual device mesh."""
    from accord_tpu.ops.kernels import jit_cache_sizes
    from accord_tpu.ops.resolver import warmup
    from accord_tpu.sim.mesh_burn import run_mesh_burn

    sizes = ((8, 60), (64, 60)) if quick else ((8, 120), (64, 120), (256, 50))
    seed = 6

    # node_tiers= pass-through (the warmup satellite): precompile the
    # node-lane kernels for small block counts before any burn runs, so
    # the warm pass below mostly exercises workload-shaped tiers
    warmup(num_buckets=128, cap=4096, batch_tiers=(8,), scatter_tiers=(8,),
           store_tiers=(1, 2), node_tiers=(2, 4))

    # warm pass: one burn per size AND mode, SAME seed/kwargs as the
    # timed legs, so every kernel shape the sweep can reach is compiled
    # before the snapshot (the widened gate below covers the FULL
    # jit_cache_sizes surface, loop-mode per-node kernels included)
    for nodes, ops in sizes:
        run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True)
        run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=False)
    cache0 = jit_cache_sizes()

    results = {}
    for nodes, ops in sizes:
        t0 = time.perf_counter()
        mesh, eng = run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True,
                                  collect_log=True)
        mesh_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        loop, leng = run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=False,
                                   collect_log=True)
        loop_s = time.perf_counter() - t0
        if mesh.log != loop.log:
            raise AssertionError(
                f"{nodes}-node node-lane burn diverged from the Python "
                f"loop ({len(mesh.log)} vs {len(loop.log)} entries)")
        snap = eng.snapshot()
        # the loop fires one device call per staged plan kernel (key and
        # range count separately); both modes stage identical plans
        loop_calls = leng.plan_kernel_launches
        mesh_calls = (snap["node_lane_dispatches"]
                      + snap["mesh_tick_fallbacks"])
        per_dispatch = loop_calls / max(mesh_calls, 1)
        results[nodes] = {
            "ops": ops,
            "acked": mesh.acked,
            "cluster_ticks": snap["cluster_ticks"],
            "node_lane_dispatches": snap["node_lane_dispatches"],
            "loop_device_calls": loop_calls,
            "nodes_per_dispatch": round(snap["nodes_per_dispatch"], 2),
            "node_pad_fraction": round(snap["node_pad_fraction"], 3),
            "mesh_tick_fallbacks": snap["mesh_tick_fallbacks"],
            "committed_per_dispatch_speedup": round(per_dispatch, 2),
            "mesh_committed_per_s": round(mesh.acked / max(mesh_s, 1e-9)),
            "loop_committed_per_s": round(loop.acked / max(loop_s, 1e-9)),
            "wall_ratio": round((mesh.acked / max(mesh_s, 1e-9))
                                / max(loop.acked / max(loop_s, 1e-9), 1e-9),
                                2),
            "history_identical": True,
        }
        if nodes >= 64 and per_dispatch < 3.0:
            raise AssertionError(
                f"committed txns per device dispatch at {nodes} nodes only "
                f"{per_dispatch:.2f}x the per-node loop "
                f"({loop_calls} loop calls vs {mesh_calls} merged; gate 3x)")

    cache1 = jit_cache_sizes()
    if cache1 != cache0:
        diff = {k: (cache0.get(k), cache1.get(k))
                for k in set(cache0) | set(cache1)
                if cache0.get(k) != cache1.get(k)}
        raise AssertionError(
            f"tick-path kernels recompiled across node-count changes in "
            f"the timed sweep: {diff}")

    # MULTICHIP: the same differential through sharded_node_tick (node
    # blocks over 'data', buckets over 'model'). Virtual devices must be
    # forced before jax's backend init, so this leg runs in a fresh
    # process with an 8-device host mesh (the dryrun_multichip pattern).
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip())
    snippet = (
        "import json, jax\n"
        "from accord_tpu.sim.mesh_burn import run_mesh_burn\n"
        "rkw = dict(num_buckets=256, initial_cap=512)\n"
        f"kw = dict(nodes=4, sharded=True, collect_log=True,\n"
        f"          resolver_kwargs=rkw)\n"
        f"sh, eng = run_mesh_burn({seed}, 40, mesh_tick=True, **kw)\n"
        f"lp, _ = run_mesh_burn({seed}, 40, mesh_tick=False, **kw)\n"
        "assert sh.log == lp.log, 'MULTICHIP node-lane burn diverged'\n"
        "print(json.dumps({'devices': len(jax.devices()),\n"
        "                  'node_lane_dispatches':\n"
        "                      eng.snapshot()['node_lane_dispatches'],\n"
        "                  'history_identical': True}))\n")
    out = subprocess.run([sys.executable, "-c", snippet], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise AssertionError(
            f"MULTICHIP leg failed: {out.stderr[-800:]}")
    multichip = json.loads(out.stdout.strip().splitlines()[-1])
    if multichip["devices"] < 8:
        raise AssertionError(
            f"MULTICHIP leg ran on {multichip['devices']} devices")

    return {
        "seed": seed,
        "sweep": {str(n): r for n, r in results.items()},
        "node_kernel_recompiles_in_sweep": 0,    # asserted above
        "multichip": multichip,
    }


# ---------------------------------------------------------------------------
# 5c. protocol megakernel: one fused device call per cluster tick
# ---------------------------------------------------------------------------

def bench_megakernel(quick: bool):
    """Megakernel sweep at 64/256/1024 nodes: the fused protocol_tick
    (resolve + finalize-CSR + quorum in ONE program per cluster tick) vs
    the unfused <=2-dispatch merge. Hard gates per size: bit-identical
    event logs, `launches_per_tick` exactly 1.0 for the fused engine,
    committed txns PER DEVICE LAUNCH strictly above the unfused path
    (the unfused tick pays a launch per plan finalize + demux slice; the
    fused tick pays one -- on dispatch-bound accelerators that collapse
    IS the committed-txn/s win), and zero compiles minted in the timed
    sweep across the FULL jit_cache_sizes surface, protocol_tick and
    lane_slice included. Wall-clock committed/s rides along un-gated,
    same convention as bench_mesh_burn: on the CPU backend both modes
    are bound by identical host-side encode, so the wall ratio hovers at
    ~1 and the structural ratio is the portable number. A MULTICHIP leg
    gates the SHARDED megakernel: on the 8-device mesh the fused tick
    lowers to sharded_protocol_tick (one shard_map program per cluster
    tick), and the leg asserts fused dispatches fired, launches per tick
    exactly 1.0, zero sharded_megakernel_fallbacks, zero post-warmup
    recompiles, and a history bit-identical to the per-node loop."""
    from accord_tpu.ops.kernels import jit_cache_sizes
    from accord_tpu.sim.mesh_burn import run_mesh_burn

    sizes = (((64, 40), (256, 30), (1024, 10)) if quick else
             ((64, 120), (256, 50), (1024, 24)))
    seed = 6

    # warm pass: both engine modes per size, SAME seed/kwargs as the
    # timed legs, so every static signature the sweep can reach
    # (protocol_tick variants included) is compiled before the snapshot
    for nodes, ops in sizes:
        run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True,
                      megakernel=True)
        run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True)
    cache0 = jit_cache_sizes()

    results = {}
    for nodes, ops in sizes:
        t0 = time.perf_counter()
        mega, meng = run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True,
                                   megakernel=True, collect_log=True)
        mega_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        unf, ueng = run_mesh_burn(seed, ops, nodes=nodes, mesh_tick=True,
                                  collect_log=True)
        unf_s = time.perf_counter() - t0
        if mega.log != unf.log:
            raise AssertionError(
                f"{nodes}-node megakernel burn diverged from the unfused "
                f"path ({len(mega.log)} vs {len(unf.log)} entries)")
        msnap, usnap = meng.snapshot(), ueng.snapshot()
        if msnap["megakernel_dispatches"] <= 0:
            raise AssertionError(f"{nodes}-node: no fused dispatch fired")
        if msnap["launches_per_tick"] != 1.0:
            raise AssertionError(
                f"{nodes}-node fused burn took "
                f"{msnap['launches_per_tick']:.2f} launches per tick "
                f"(gate: exactly 1)")
        per_launch = (mega.acked / max(meng.protocol_launches, 1)) \
            / max(unf.acked / max(ueng.protocol_launches, 1), 1e-9)
        if per_launch <= 1.0:
            raise AssertionError(
                f"{nodes}-node committed txns per device launch only "
                f"{per_launch:.2f}x the unfused path "
                f"({meng.protocol_launches} fused launches vs "
                f"{ueng.protocol_launches}; gate: strictly above 1)")
        results[nodes] = {
            "ops": ops,
            "acked": mega.acked,
            "cluster_ticks": msnap["cluster_ticks"],
            "megakernel_dispatches": msnap["megakernel_dispatches"],
            "launches_per_tick": msnap["launches_per_tick"],
            "unfused_launches_per_tick": round(
                usnap["launches_per_tick"], 2),
            "committed_per_launch_speedup": round(per_launch, 2),
            "mega_committed_per_s": round(mega.acked / max(mega_s, 1e-9), 1),
            "unfused_committed_per_s": round(unf.acked / max(unf_s, 1e-9), 1),
            "wall_ratio": round((mega.acked / max(mega_s, 1e-9))
                                / max(unf.acked / max(unf_s, 1e-9), 1e-9),
                                2),
            "history_identical": True,
        }

    cache1 = jit_cache_sizes()
    if cache1 != cache0:
        diff = {k: (cache0.get(k), cache1.get(k))
                for k in set(cache0) | set(cache1)
                if cache0.get(k) != cache1.get(k)}
        raise AssertionError(
            f"megakernel sweep minted compiles in the timed window: {diff}")

    # MULTICHIP: megakernel=True on the sharded 8-device mesh lowers the
    # fused tick to sharded_protocol_tick -- ONE shard_map program per
    # cluster tick. Gate the fused sharded path directly: dispatches
    # fired, exactly one launch per tick, zero fallbacks to the unfused
    # pair, zero post-warmup recompiles, history == per-node loop.
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip())
    snippet = (
        "import json, jax\n"
        "from accord_tpu.ops.kernels import jit_cache_sizes\n"
        "from accord_tpu.sim.mesh_burn import run_mesh_burn\n"
        "rkw = dict(num_buckets=256, initial_cap=512)\n"
        "kw = dict(nodes=4, sharded=True, collect_log=True,\n"
        "          resolver_kwargs=rkw)\n"
        f"run_mesh_burn({seed}, 40, mesh_tick=True, megakernel=True,"
        " **kw)\n"
        f"run_mesh_burn({seed}, 40, mesh_tick=False, **kw)\n"
        "cache0 = jit_cache_sizes()\n"
        f"sh, eng = run_mesh_burn({seed}, 40, mesh_tick=True,\n"
        f"                        megakernel=True, **kw)\n"
        f"lp, _ = run_mesh_burn({seed}, 40, mesh_tick=False, **kw)\n"
        "assert sh.log == lp.log, 'MULTICHIP megakernel burn diverged'\n"
        "cache1 = jit_cache_sizes()\n"
        "assert cache1 == cache0, \\\n"
        "    f'warm sharded burn minted compiles: {cache0} -> {cache1}'\n"
        "snap = eng.snapshot()\n"
        "assert snap['megakernel_dispatches'] > 0, \\\n"
        "    'sharded mesh never took the fused sharded path'\n"
        "assert snap['launches_per_tick'] == 1.0, \\\n"
        "    f\"sharded fused burn took {snap['launches_per_tick']:.2f}"
        " launches/tick\"\n"
        "assert snap['sharded_megakernel_fallbacks'] == 0, \\\n"
        "    f\"{snap['sharded_megakernel_fallbacks']} ticks fell back to"
        " the unfused pair\"\n"
        "print(json.dumps({'devices': len(jax.devices()),\n"
        "                  'megakernel_dispatches':"
        " snap['megakernel_dispatches'],\n"
        "                  'launches_per_tick':"
        " snap['launches_per_tick'],\n"
        "                  'sharded_megakernel_fallbacks': 0,\n"
        "                  'recompiles_post_warmup': 0,\n"
        "                  'history_identical': True}))\n")
    out = subprocess.run([sys.executable, "-c", snippet], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise AssertionError(
            f"MULTICHIP megakernel leg failed: {out.stderr[-800:]}")
    multichip = json.loads(out.stdout.strip().splitlines()[-1])
    if multichip["devices"] < 8:
        raise AssertionError(
            f"MULTICHIP megakernel leg ran on {multichip['devices']} devices")

    largest = results[max(results)]
    return {
        "seed": seed,
        # headline keys (main() grafts messages_per_host_callback from the
        # message-plane leg next to these)
        "launches_per_tick": 1.0,    # asserted per size above
        "sharded_launches_per_tick": multichip["launches_per_tick"],
        "wall_committed_per_s": largest["mega_committed_per_s"],
        "sweep": {str(n): r for n, r in results.items()},
        "recompiles_in_sweep": 0,    # asserted above
        "multichip": multichip,
    }


def bench_message_plane(quick: bool):
    """Device message plane sweep at 64/256/1024 nodes: replica traffic
    routed through the mailbox arena inside the fused protocol_tick
    (sim/network.DeviceMessageNetwork + ops/mailbox.py) vs the per-message
    host event baseline. Hard gates per size: bit-identical committed
    histories, `launches_per_tick` exactly 1.0 (the mailbox stage rides
    the one fused launch, it never adds one), zero mailbox overflow spills
    and zero verify fallbacks in steady state; across the sweep: host
    message callbacks collapsed >= 10x (`messages_per_host_callback`) and
    zero compiles minted in the timed window over the full
    jit_cache_sizes() surface. Two parity side legs ride along gated on
    history equality only: a chaos leg (drops + partitions) and a 3-region
    ASYMMETRIC regional-latency LinkMatrix leg that the host path also
    runs -- one matrix feeding both modes bit-identically. A MULTICHIP
    leg reruns the contract on the sharded 8-device mesh, where the
    mailbox stage rides sharded_protocol_tick's cross-shard all_to_all
    hop: same hard gates (lpt exactly 1.0, zero spills/fallbacks, >= 10x
    collapse, zero post-warmup recompiles, history == host path)."""
    from accord_tpu.ops.kernels import jit_cache_sizes
    from accord_tpu.sim.mesh_burn import run_mesh_burn
    from accord_tpu.sim.network import LinkMatrix

    sizes = (((64, 30), (256, 20), (1024, 8)) if quick else
             ((64, 60), (256, 30), (1024, 12)))
    seed = 6
    # rf=5: the callback-collapse ratio is message density against the
    # fixed tick cadence, and a wider electorate is the honest way to get
    # cluster-scale message volume at benchable op counts
    base = dict(rf=5, concurrency=24, megakernel=True, collect_log=True)
    chaos_kw = dict(nodes=64, chaos_drop=0.05, chaos_partitions=True,
                    **base)
    chaos_ops = 30 if quick else 60
    regional_kw = dict(nodes=64, link_matrix=LinkMatrix.regional(64),
                       **base)
    regional_ops = 30 if quick else 60

    # warm pass: every leg both modes, SAME seed/kwargs as the timed
    # sweep, so each static signature (mailbox tiers included) compiles
    # before the snapshot
    for nodes, ops in sizes:
        run_mesh_burn(seed, ops, nodes=nodes, device_messages=True, **base)
        run_mesh_burn(seed, ops, nodes=nodes, **base)
    run_mesh_burn(seed, chaos_ops, device_messages=True, **chaos_kw)
    run_mesh_burn(seed, chaos_ops, **chaos_kw)
    run_mesh_burn(seed, regional_ops, device_messages=True, **regional_kw)
    run_mesh_burn(seed, regional_ops, **regional_kw)
    cache0 = jit_cache_sizes()

    results = {}
    fires = batches = 0
    for nodes, ops in sizes:
        t0 = time.perf_counter()
        dev, deng = run_mesh_burn(seed, ops, nodes=nodes,
                                  device_messages=True, **base)
        dev_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        host, _ = run_mesh_burn(seed, ops, nodes=nodes, **base)
        host_s = time.perf_counter() - t0
        if dev.log != host.log:
            raise AssertionError(
                f"{nodes}-node device-message burn diverged from the host "
                f"path ({len(dev.log)} vs {len(host.log)} entries)")
        c = dev.counters
        if c["launches_per_tick"] != 1.0:
            raise AssertionError(
                f"{nodes}-node: mailbox routing cost extra launches "
                f"({c['launches_per_tick']:.2f} per tick; gate: exactly 1)")
        if c["mailbox_overflow_spills"] != 0:
            raise AssertionError(
                f"{nodes}-node: {c['mailbox_overflow_spills']} mailbox "
                f"spills in steady state (gate: 0)")
        if c["mailbox_verify_fallbacks"] != 0:
            raise AssertionError(
                f"{nodes}-node: {c['mailbox_verify_fallbacks']} device "
                f"payloads failed verification (gate: 0)")
        if c["device_messages_delivered"] <= 0:
            raise AssertionError(f"{nodes}-node: no device delivery")
        fires += c["message_plane_fires"]
        batches += c["message_plane_batches"]
        results[nodes] = {
            "ops": ops,
            "acked": dev.acked,
            "device_messages_delivered": c["device_messages_delivered"],
            "mailbox_depth_high_water": c["mailbox_depth_high_water"],
            "messages_per_host_callback": c["messages_per_host_callback"],
            "launches_per_tick": c["launches_per_tick"],
            "dev_committed_per_s": round(dev.acked / max(dev_s, 1e-9), 1),
            "host_committed_per_s": round(host.acked / max(host_s, 1e-9), 1),
            "history_identical": True,
        }

    collapse = fires / max(batches, 1)
    if collapse < 10.0:
        raise AssertionError(
            f"host message callbacks only collapsed {collapse:.1f}x across "
            f"the sweep (gate: >= 10x)")

    # chaos parity: seeded drops + partitions through the mailbox plane
    # must not shift any rng stream
    dev, _ = run_mesh_burn(seed, chaos_ops, device_messages=True,
                           **chaos_kw)
    host, _ = run_mesh_burn(seed, chaos_ops, **chaos_kw)
    if dev.log != host.log:
        raise AssertionError("chaos leg diverged under device messages")
    chaos = {"ops": chaos_ops, "history_identical": True,
             "mailbox_verify_fallbacks":
                 dev.counters["mailbox_verify_fallbacks"]}

    # regional parity: the 3-region asymmetric matrix runs through BOTH
    # paths (one LinkMatrix feeds the host dict and the device masks)
    dev, _ = run_mesh_burn(seed, regional_ops, device_messages=True,
                           **regional_kw)
    host, _ = run_mesh_burn(seed, regional_ops, **regional_kw)
    if dev.log != host.log:
        raise AssertionError("regional-latency leg diverged between paths")
    regional = {"ops": regional_ops, "regions": 3,
                "history_identical_both_paths": True,
                "messages_per_host_callback":
                    dev.counters["messages_per_host_callback"]}

    cache1 = jit_cache_sizes()
    if cache1 != cache0:
        diff = {k: (cache0.get(k), cache1.get(k))
                for k in set(cache0) | set(cache1)
                if cache0.get(k) != cache1.get(k)}
        raise AssertionError(
            f"message-plane sweep minted compiles in the timed window: "
            f"{diff}")

    # MULTICHIP: the mailbox stage on the sharded 8-device mesh -- emit
    # lanes grouped by (src shard, dst shard), shipped by the tiled
    # all_to_all inside sharded_protocol_tick. Same contract as the
    # single-device sweep, gated in-subprocess: one launch per tick,
    # zero spills / verify fallbacks / unfused fallbacks, >= 10x host
    # callback collapse, zero post-warmup recompiles, and a history
    # bit-identical to the host message path.
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          ).strip())
    snippet = (
        "import json, jax\n"
        "from accord_tpu.ops.kernels import jit_cache_sizes\n"
        "from accord_tpu.sim.mesh_burn import run_mesh_burn\n"
        "kw = dict(nodes=16, rf=5, concurrency=32, sharded=True,\n"
        "          megakernel=True, collect_log=True)\n"
        "# warm BOTH modes: the host-path run's tick statics (no mailbox\n"
        "# stage) compile separately from the device-message tick's\n"
        f"run_mesh_burn({seed}, 50, device_messages=True, **kw)\n"
        f"run_mesh_burn({seed}, 50, **kw)\n"
        "cache0 = jit_cache_sizes()\n"
        f"dev, eng = run_mesh_burn({seed}, 50, device_messages=True,"
        " **kw)\n"
        f"host, _ = run_mesh_burn({seed}, 50, **kw)\n"
        "assert dev.log == host.log, 'MULTICHIP message leg diverged'\n"
        "assert jit_cache_sizes() == cache0, \\\n"
        "    'warm sharded message burn minted compiles'\n"
        "c = dev.counters\n"
        "assert c['launches_per_tick'] == 1.0, c['launches_per_tick']\n"
        "assert c['mailbox_overflow_spills'] == 0\n"
        "assert c['mailbox_verify_fallbacks'] == 0\n"
        "assert c['sharded_megakernel_fallbacks'] == 0\n"
        "assert c['device_messages_delivered'] > 0\n"
        "assert c['messages_per_host_callback'] >= 10.0, \\\n"
        "    c['messages_per_host_callback']\n"
        "print(json.dumps({'devices': len(jax.devices()),\n"
        "                  'launches_per_tick': 1.0,\n"
        "                  'messages_per_host_callback':\n"
        "                      c['messages_per_host_callback'],\n"
        "                  'device_messages_delivered':\n"
        "                      c['device_messages_delivered'],\n"
        "                  'sharded_megakernel_fallbacks': 0,\n"
        "                  'recompiles_post_warmup': 0,\n"
        "                  'history_identical': True}))\n")
    out = subprocess.run([sys.executable, "-c", snippet], env=env,
                         capture_output=True, text=True, timeout=1800)
    if out.returncode != 0:
        raise AssertionError(
            f"MULTICHIP message-plane leg failed: {out.stderr[-800:]}")
    multichip = json.loads(out.stdout.strip().splitlines()[-1])
    if multichip["devices"] < 8:
        raise AssertionError(
            f"MULTICHIP message-plane leg ran on "
            f"{multichip['devices']} devices")

    return {
        "seed": seed,
        "messages_per_host_callback": round(collapse, 2),
        "sharded_launches_per_tick": multichip["launches_per_tick"],
        "sweep": {str(n): r for n, r in results.items()},
        "chaos": chaos,
        "regional": regional,
        "recompiles_in_sweep": 0,    # asserted above
        "multichip": multichip,
    }


# ---------------------------------------------------------------------------
# recovery storm: device-compacted frontier + recovery scans at 10k in-flight
# ---------------------------------------------------------------------------

def bench_recovery_storm(quick: bool):
    """The exec/recovery plane's device-compaction contract, three legs.

    STORM BURN: same-seed crash-restart burns (cmd arena on), recovery
    candidate selection via the host walk vs ONE kernels.recovery_scan
    device query per progress sweep feeding _sweep_stuck_waiters. Gates:
    bit-identical event logs, device dispatches fired, zero counted
    checksum fallbacks / out_cap overflows. An exec twin rides along:
    standalone compacted ExecPlane harvest vs the frontier block staged
    INTO the megakernel (exec_in_megakernel=True) -- bit-identical logs
    and launches_per_tick exactly 1.0 with exec traffic included.

    EXEC READBACK @10k: frontier_compact over 5 x 2048-row wait-graph
    arenas (10240 in-flight waiters) through the real _consume_compact
    accounting. Gate: compacted readback bytes (indptr + row list + csum)
    STRICTLY below the full packed-bitmask equivalent. (Burn-scale arenas
    stay at 1024 rows where the padded out_cap row list can exceed the
    tiny full bitmask -- the win is an in-flight-scale property, so it is
    gated here and only reported for the burns.)

    SCAN @10k: real PreAccept/Commit/Apply streams park ~10k rows in one
    CmdPlane (a third driven to APPLIED -- terminals must be excluded),
    stall ages synthesized, then the timed window compares the pure-python
    host walk and the numpy shadow twin against the device query. Gates:
    candidate lists bit-identical on every scan, one device dispatch per
    scan, zero fallbacks/overflows, and zero compiles minted in the timed
    window across the FULL jit_cache_sizes surface (the recovery_tiers=
    warmup pass-through plus an organic warm sweep cover the tier
    ladder). Host-walk vs device-query wall time is reported un-gated:
    on CPU a dispatch is a function call, so the portable number is the
    readback/launch structure, not the wall ratio."""
    import random as _random

    from accord_tpu.ops.cmd_plane import CmdOp, CmdPlane
    from accord_tpu.ops.exec_plane import _consume_compact
    from accord_tpu.ops.kernels import (CMD_ST_APPLIED, CMD_ST_PRE_ACCEPTED,
                                        FRONTIER_OUT_TIERS,
                                        RECOVERY_OUT_TIERS, frontier_compact,
                                        jit_cache_sizes)
    from accord_tpu.ops.resolver import warmup
    from accord_tpu.ops.tiers import OutCapTiers
    from accord_tpu.primitives.deps import Deps
    from accord_tpu.primitives.keyspace import Keys
    from accord_tpu.primitives.timestamp import TxnKind
    from accord_tpu.primitives.txn import Txn
    from accord_tpu.sim.cluster import Cluster, ClusterConfig
    from accord_tpu.sim.list_store import ListQuery, ListRead, ListUpdate
    from accord_tpu.sim.mesh_burn import run_mesh_burn
    import jax.numpy as jnp

    # -- leg 1: crash-restart storm burn, host walk vs device scan ----------
    storm_kw = dict(ops=24 if quick else 48, nodes=4, rf=3,
                    stores_per_node=2, key_count=24, concurrency=8,
                    collect_log=True, cmd_plane=True, crash_restart=True,
                    megakernel=True)
    rh, _ = run_mesh_burn(17, recovery_scan="host", **storm_kw)
    rd, _ = run_mesh_burn(17, recovery_scan="device", **storm_kw)
    if rh.log != rd.log:
        raise AssertionError(
            f"device recovery scan diverged from the host walk "
            f"({len(rh.log)} vs {len(rd.log)} entries)")
    if rd.counters.get("recovery_scan_dispatches", 0) <= 0:
        raise AssertionError("storm burn issued no recovery_scan dispatches")
    if rd.counters.get("recovery_scan_fallbacks", 0) \
            or rd.counters.get("recovery_scan_overflows", 0):
        raise AssertionError(
            f"storm burn degraded: "
            f"{rd.counters.get('recovery_scan_fallbacks', 0)} checksum "
            f"fallbacks, {rd.counters.get('recovery_scan_overflows', 0)} "
            f"overflows (gate: zero in steady state)")
    storm = {
        "ops": storm_kw["ops"],
        "acked": rd.acked,
        "recovery_scan_dispatches": rd.counters["recovery_scan_dispatches"],
        "recovery_scan_candidates":
            rd.counters.get("recovery_scan_candidates", 0),
        "recovery_scan_device_s":
            round(rd.counters.get("recovery_scan_device_s", 0.0), 4),
        "recovery_scan_host_s":
            round(rh.counters.get("recovery_scan_host_s", 0.0), 4),
        "fallbacks": 0,
        "overflows": 0,
        "history_identical": True,
    }

    # -- leg 1b: exec frontier staged into the megakernel -------------------
    exec_kw = dict(ops=24 if quick else 40, nodes=4, rf=3, stores_per_node=2,
                   key_count=24, concurrency=8, collect_log=True,
                   exec_plane=True, exec_compact=True, megakernel=True)
    e0, _ = run_mesh_burn(13, **exec_kw)
    e1, _ = run_mesh_burn(13, exec_in_megakernel=True, **exec_kw)
    if e0.log != e1.log:
        raise AssertionError(
            f"exec-in-megakernel burn diverged from the standalone "
            f"compacted harvest ({len(e0.log)} vs {len(e1.log)} entries)")
    if e1.counters["launches_per_tick"] != 1.0:
        raise AssertionError(
            f"exec traffic broke launch fusion: "
            f"{e1.counters['launches_per_tick']:.2f} launches per tick "
            f"(gate: exactly 1)")
    if e1.counters.get("exec_scan_blocks", 0) <= 0 \
            or e1.counters.get("exec_coord.staged_blocks", 0) <= 0:
        raise AssertionError("no exec blocks rode the fused launches")
    if e1.counters.get("exec_coord.compact_fallbacks", 0) \
            or e1.counters.get("exec.compact_fallbacks", 0):
        raise AssertionError("exec compact harvest degraded to the bitmask")

    def _readback(r):
        return (r.counters.get("exec.readback_bytes", 0)
                + r.counters.get("exec_coord.readback_bytes", 0),
                r.counters.get("exec.readback_full_equiv", 0)
                + r.counters.get("exec_coord.readback_full_equiv", 0))

    burn_rb, burn_full = _readback(e1)
    exec_mk = {
        "ops": exec_kw["ops"],
        "acked": e1.acked,
        "launches_per_tick": 1.0,
        "exec_scan_blocks": e1.counters["exec_scan_blocks"],
        "exec_flush_ticks": e1.counters.get("exec_flush_ticks", 0),
        "staged_blocks": e1.counters["exec_coord.staged_blocks"],
        "burn_readback_bytes": burn_rb,
        "burn_readback_full_equiv": burn_full,
        "history_identical": True,
    }

    # -- leg 2: compacted frontier readback at 10k in-flight ----------------
    ecap, nplanes, per_plane = 2048, 5, 40
    words = ecap // 32
    frng = np.random.default_rng(23)
    neg = np.int32(np.iinfo(np.int32).min)
    planes, expected = [], []
    for _ in range(nplanes):
        rel = np.sort(frng.choice(np.arange(2, ecap), per_plane,
                                  replace=False))
        adj = np.zeros((ecap, ecap), bool)
        # rows 0/1 gate each other; every other non-released row waits on
        # row 0 (undecided executeAt: the commit-wait gates) -- ALL ecap
        # rows stay pending (in flight), exactly `rel` clears its gates
        adj[0, 1] = adj[1, 0] = True
        gated = np.ones(ecap, bool)
        gated[rel] = False
        gated[:2] = False
        adj[gated, 0] = True
        planes.append((jnp.asarray(adj),
                       jnp.full((ecap, 3), neg, jnp.int32),
                       jnp.zeros(ecap, bool),       # applied
                       jnp.ones(ecap, bool),        # pending: all in flight
                       jnp.zeros(ecap, bool)))      # awaits_all
        expected.append(rel.tolist())

    class _FPlane:
        def __init__(self):
            self.calls = []

        def _apply_rows(self, rows, gen):
            self.calls.append((list(rows), gen))

        def _apply_frontier(self, packed, gen):
            raise AssertionError(
                "10k-in-flight leg fell back to the bitmask decode")

    class _FOwner:
        readback_bytes = 0
        readback_full_equiv = 0
        compact_fallbacks = 0
        compact_overflows = 0
        _out_tiers = None

        def _observe_bound(self, total):
            pass

    out_tiers = OutCapTiers(FRONTIER_OUT_TIERS, FRONTIER_OUT_TIERS[-1] * 2)
    out_cap = out_tiers.pick(nplanes * per_plane)
    res = frontier_compact(tuple(planes), out_cap=out_cap)
    host = tuple(np.asarray(x) for x in res[:3])
    if int(host[0][-1]) != nplanes * per_plane:
        raise AssertionError(
            f"frontier bound {int(host[0][-1])} != released "
            f"{nplanes * per_plane}")
    stubs = [_FPlane() for _ in range(nplanes)]
    owner = _FOwner()
    entries = [(p, (s * words, (s + 1) * words), 1)
               for s, p in enumerate(stubs)]
    _consume_compact(owner, res, host, entries, out_cap)
    for s, p in enumerate(stubs):
        if p.calls != [(expected[s], 1)]:
            raise AssertionError(f"plane {s} release set diverged")
    if owner.compact_fallbacks or owner.compact_overflows:
        raise AssertionError("10k-in-flight compaction degraded")
    if not owner.readback_bytes < owner.readback_full_equiv:
        raise AssertionError(
            f"compacted readback {owner.readback_bytes}B not strictly "
            f"below the full-row equivalent {owner.readback_full_equiv}B "
            f"at {nplanes * ecap} in-flight")

    # -- leg 3: recovery scan at 10k in-flight, timed -----------------------
    n = 2_048 if quick else 10_240
    arena_cap = 16_384
    chunk = 512
    stall_ms = 1_000
    ks = (0, 20, 40, 60)
    iters = 6 if quick else 15

    # recovery_tiers= pass-through (the warmup satellite): every rung the
    # hysteresis picker can pin at this arena cap, floor included, plus
    # the cmd-plane coverage the stream phase needs (already cached from
    # bench_cmd_plane's own warmup -- process-global jit cache)
    warmup(num_buckets=64, cap=1024, batch_tiers=(), scatter_tiers=(),
           store_tiers=(1,), range_out_tiers=(), cmd_caps=(arena_cap,),
           cmd_op_tiers=(chunk,), cmd_promote_modes=(True,),
           recovery_tiers=RECOVERY_OUT_TIERS + (RECOVERY_OUT_TIERS[-1] * 2,))

    cluster = Cluster(1, ClusterConfig(num_nodes=1, rf=1, num_shards=1,
                                       stores_per_node=1, progress=False))
    node = cluster.nodes[1]
    store = node.command_stores.stores[0]
    srng = _random.Random(7)
    txns = []
    for v in range(n):
        keys = Keys(sorted(srng.sample(range(1, 257), srng.randint(1, 3))))
        txn = Txn(TxnKind.WRITE, keys, read=ListRead(keys),
                  update=ListUpdate(keys, v), query=ListQuery())
        tid = node.next_txn_id(txn.kind, txn.domain)
        txns.append((tid, node.compute_route(txn),
                     txn.slice(store.ranges, include_query=False)))
    plane = CmdPlane(store, initial_cap=arena_cap, key_cap=1024, kpad=4,
                     apply_to_store=False)
    eas = {}
    for i in range(0, n, chunk):
        span = txns[i:i + chunk]
        res = plane.eval_batch([CmdOp.preaccept(t, p, r)
                                for t, r, p in span])
        for (tid, *_), r in zip(span, res):
            eas[tid] = r.execute_at
    # drive the last third to APPLIED: terminals the scan must skip
    tail = txns[n - n // 3:]
    for i in range(0, len(tail), chunk):
        span = tail[i:i + chunk]
        plane.eval_batch([CmdOp.commit(t, r, p, eas[t], Deps.NONE)
                          for t, r, p in span])
        plane.eval_batch([CmdOp.apply(t, r, p, eas[t], Deps.NONE)
                          for t, r, p in span])

    # synthetic stall ages (the storm burn above exercises the organic
    # _touch path): ~9-15% of the live band stalls past each swept `now`
    arng = np.random.default_rng(29)
    now0 = int(node.now_millis()) + 100_000
    plane.touched_h[:plane.n_rows] = \
        now0 - arng.integers(0, 1_100, plane.n_rows, dtype=np.int32)
    plane._touched_stale = True

    st_h, th_h = plane.status_h, plane.touched_h

    def py_walk(now):
        # the pre-compaction host walk: per-txn python predicate over the
        # whole live set, one dict/array probe each
        out = []
        for tid, row in plane.row_of.items():
            s = int(st_h[row])
            if CMD_ST_PRE_ACCEPTED <= s < CMD_ST_APPLIED \
                    and now - int(th_h[row]) >= stall_ms:
                out.append(tid)
        return out

    # organic warm sweep: same (now, stall) shapes as the timed window
    for k in ks:
        plane.recovery_scan_device(now0 + k, stall_ms)
    cache0 = jit_cache_sizes()
    d0 = plane.recovery_scan_dispatches
    tdev0 = plane.recovery_scan_device_s
    thost0 = plane.recovery_scan_host_s
    fb0 = plane.recovery_scan_fallbacks
    ov0 = plane.recovery_scan_overflows

    walk_s = 0.0
    totals = []
    for _ in range(iters):
        for k in ks:
            now = now0 + k
            dev = plane.recovery_scan_device(now, stall_ms)
            twin = plane.recovery_scan_host(now, stall_ms)
            t0 = time.perf_counter()
            walked = py_walk(now)
            walk_s += time.perf_counter() - t0
            if dev != twin or dev != walked:
                raise AssertionError(
                    f"scan diverged at now+{k}: device {len(dev)} / twin "
                    f"{len(twin)} / walk {len(walked)} candidates")
            totals.append(len(dev))
    cache1 = jit_cache_sizes()

    if cache1 != cache0:
        diff = {k: (cache0.get(k), cache1.get(k))
                for k in set(cache0) | set(cache1)
                if cache0.get(k) != cache1.get(k)}
        raise AssertionError(
            f"recovery scan window minted compiles: {diff}")
    scans = iters * len(ks)
    if plane.recovery_scan_dispatches - d0 != scans:
        raise AssertionError(
            f"{plane.recovery_scan_dispatches - d0} device dispatches for "
            f"{scans} scans (gate: exactly one query per scan)")
    if plane.recovery_scan_fallbacks - fb0 \
            or plane.recovery_scan_overflows - ov0:
        raise AssertionError("timed scans degraded to the host walk")
    dev_s = plane.recovery_scan_device_s - tdev0
    twin_s = plane.recovery_scan_host_s - thost0

    return {
        "storm": storm,
        "exec_megakernel": exec_mk,
        "exec_inflight": nplanes * ecap,
        "exec_readback_bytes": owner.readback_bytes,
        "exec_readback_full_equiv": owner.readback_full_equiv,
        "scan": {
            "inflight": n,
            "arena_cap": arena_cap,
            "scans": scans,
            "candidates_min": min(totals),
            "candidates_max": max(totals),
            "python_walk_s": round(walk_s, 4),
            "numpy_twin_s": round(twin_s, 4),
            "device_s": round(dev_s, 4),
            "walk_vs_device": round(walk_s / max(dev_s, 1e-9), 2),
            "fallbacks": 0,                 # asserted above
            "overflows": 0,                 # asserted above
            "recompiles_in_window": 0,      # asserted above
        },
    }


# ---------------------------------------------------------------------------
# 6. obs overhead: the disabled flight recorder must cost ~nothing
# ---------------------------------------------------------------------------

def bench_obs_overhead():
    """The overhead gate: every hot path in the stack carries recorder
    calls compiled in, so a DISABLED call must stay a single attribute
    check -- measured here and asserted under a generous noise ceiling
    (an enabled-call figure rides along for scale)."""
    import timeit

    from accord_tpu.obs.trace import REC

    assert not REC.enabled, "recorder left enabled by an earlier leg"
    n = 200_000
    stmt = lambda: REC.instant(0, "bench", "x", 0)  # noqa: E731
    disabled_s = timeit.timeit(stmt, number=n)
    saved_len = REC._buf.maxlen
    REC.configure(capacity=1 << 12)
    REC.enabled = True
    try:
        enabled_s = timeit.timeit(stmt, number=n)
    finally:
        REC.enabled = False
        REC.clear()
        REC.configure(capacity=saved_len)
    disabled_ns = disabled_s / n * 1e9
    gate_ns = 1500.0  # interpreter-noise ceiling; a real regression is 10x+
    if disabled_ns > gate_ns:
        raise AssertionError(
            f"disabled flight-recorder call costs {disabled_ns:.0f}ns "
            f"(gate {gate_ns:.0f}ns): the disabled path stopped being a "
            f"single attribute check")
    return {
        "calls": n,
        "disabled_ns_per_call": round(disabled_ns, 1),
        "enabled_ns_per_call": round(enabled_s / n * 1e9, 1),
        "gate_ns": gate_ns,
    }


def main(argv=None) -> int:
    global TRACE_BASE
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="dump a Perfetto trace per leg to PATH.<leg>.json")
    args = ap.parse_args(argv)
    TRACE_BASE = args.trace
    try:
        import jax
        device = jax.devices()[0].platform

        from accord_tpu.ops.resolver import warmup
        t0 = time.perf_counter()
        # store_tiers=(1, 2): the e2e cluster runs 2 stores/node, so the
        # fused cross-store tiers must be pre-compiled for its
        # zero-recompile assertion (single-group dispatches reuse the
        # plain kernels, warmed by store tier 1)
        # exec_caps=(1024,): the exec-plane leg's wait-graph arenas start at
        # 1024 rows; warm their per-field lane-delta scatters too
        # out_tiers: the OutCapTiers ladder rungs the e2e burn's hysteresis
        # picker can pin; with the finalize kernels now under the strict
        # zero-recompile assertion these must be pre-compiled. The quick
        # burn (200 ops) stays inside the first three rungs; the full burn
        # (800 ops, 1024 in flight) piles hot-key populations high enough
        # to pin 131072, and the headroomed estimate can overshoot the
        # observed peak by one rung on a burst, hence 262144.
        e2e_outs = ((256, 2048, 16384) if args.quick else
                    (256, 2048, 16384, 32768, 65536, 131072, 262144))
        # range_out_tiers=(256,): durability sync txns register RANGE
        # rows, so key subjects stab the interval arena -- one small
        # range compaction shape per burn (rents x nvalid stays tiny)
        warmup(num_buckets=E2E_BUCKETS, cap=E2E_ARENA_CAP,
               batch_tiers=(8, 64, 128, 256), scatter_tiers=(8, 64),
               store_tiers=(1, 2), exec_caps=(1024,),
               out_tiers=e2e_outs, range_out_tiers=(256,))
        # the large replay's admission windows dispatch anywhere between 129
        # and PIPE_BATCH subjects (~4 keys each), so every intermediate
        # subject tier and the 4096-entry CSR tier must be pre-compiled for
        # the zero-recompile assertion to hold in the timed window (single
        # store per node: no fused tiers needed)
        warmup(num_buckets=PIPE_BUCKETS, cap=PIPE_CAP,
               batch_tiers=(8, 64, 128, 256, 512, PIPE_BATCH),
               scatter_tiers=(8, 64),
               nnz_tiers=(32, 256, 2048, 4096), store_tiers=(1,))
        # finalized-CSR compaction tiers, matched per batch tier: out_cap
        # is the dispatch's exact popcount bound padded to a tier, and for
        # this workload bound ~= flat_keys x mean key population (~40 full,
        # ~8 quick). A dispatch padded to batch tier T carries anywhere
        # from prev_tier+1 to T real subjects, so each tier's bound spans
        # a RANGE of out buckets (both bench modes included); nnz edge
        # tiers cover in-item key dupes dipping flat_keys under a
        # boundary. Key-only workload: skip the range compaction tiers.
        for bt, nts, outs in (
                (8, (32,), (256, 2048)),
                (64, (256,), (2048, 16384)),
                (128, (256, 2048), (2048, 16384, 32768)),
                (256, (2048,), (16384, 32768, 65536)),
                (512, (2048,), (16384, 32768, 65536, 131072)),
                (PIPE_BATCH, (2048, 4096),
                 (16384, 32768, 65536, 131072, 262144)),
        ):
            warmup(num_buckets=PIPE_BUCKETS, cap=PIPE_CAP, batch_tiers=(bt,),
                   scatter_tiers=(), nnz_tiers=nts, store_tiers=(1,),
                   out_tiers=outs, range_out_tiers=())
        warm_s = time.perf_counter() - t0

        obs_overhead = bench_obs_overhead()
        pipeline = _traced("pipeline", bench_pipeline, args.quick)
        dag = _traced("dag", bench_dag, args.quick)
        maelstrom = _traced("maelstrom", bench_maelstrom, args.quick)
        # bench_e2e scopes its own trace to the first device attempt (the
        # whole-leg wrapper would mix three burns into one stream)
        e2e = bench_e2e(args.quick)
        range_mix = _traced("range_mix", bench_range_mix, args.quick)
        device_chaos = _traced("device_chaos", bench_device_chaos,
                               args.quick)
        pad_tiers = _traced("pad_tiers", bench_pad_tiers, args.quick)
        exec_plane = _traced("exec_plane", bench_exec_plane, args.quick)
        cmd_plane = _traced("cmd_plane", bench_cmd_plane, args.quick)
        mesh_burn = _traced("mesh_burn", bench_mesh_burn, args.quick)
        megakernel = _traced("megakernel", bench_megakernel, args.quick)
        message_plane = _traced("message_plane", bench_message_plane,
                                args.quick)
        megakernel["messages_per_host_callback"] = \
            message_plane["messages_per_host_callback"]
        recovery_storm = _traced("recovery_storm", bench_recovery_storm,
                                 args.quick)
        # subprocess leg last: it runs in its OWN processes (each does its
        # own warmup), so the parent's jit caches and trace are untouched
        serve = bench_serve(args.quick)

        print(json.dumps({
            "metric": "preaccept_deps_block_us_at_10k_inflight",
            "value": pipeline["device_block_us"],
            "unit": "us",
            "vs_baseline": pipeline["speedup_blocking"],
            # compacted exec-frontier readback vs the full packed-bitmask
            # fetch at 10k in-flight (compacted < full asserted in the
            # recovery_storm leg)
            "exec_readback_bytes": recovery_storm["exec_readback_bytes"],
            "exec_readback_full_equiv":
                recovery_storm["exec_readback_full_equiv"],
            "details": {
                "device": device,
                "warmup_s": round(warm_s, 1),
                "pipeline": pipeline,
                "dag_100k": dag,
                "maelstrom": maelstrom,
                "e2e_contended": e2e,
                "range_mix": range_mix,
                "device_chaos": device_chaos,
                "pad_store_tiers": pad_tiers,
                "exec_plane": exec_plane,
                "cmd_plane": cmd_plane,
                "mesh_burn": mesh_burn,
                "megakernel": megakernel,
                "message_plane": message_plane,
                "recovery_storm": recovery_storm,
                "serve": serve,
                "obs_overhead": obs_overhead,
            },
        }))
        return 0
    except BaseException as e:  # noqa: BLE001 -- one parseable line, rc 1
        print(json.dumps({
            "metric": "preaccept_deps_block_us_at_10k_inflight", "value": 0,
            "unit": "us", "vs_baseline": 0.0,
            "details": {"error": f"{type(e).__name__}: {e}",
                        "trace": traceback.format_exc()[-1500:]},
        }))
        return 1


if __name__ == "__main__":
    sys.exit(main())
